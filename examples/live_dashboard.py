"""Live dashboard: server-side reads without a client replica.

The TPU serving path materializes every common channel type on device
(server/tpu_sequencer.py), so a read-only surface — a metrics dashboard, a
search indexer, a cold-start snapshot service — can read document state
STRAIGHT FROM THE SEQUENCER without loading a container or replaying ops.
The reference needs a headless client (server/headless-agent) for this;
here it is one call against the partition lambda's device lanes.

Run: python -m examples.live_dashboard
"""

from __future__ import annotations

from typing import Dict

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.loader.container import Loader
from fluidframework_tpu.loader.drivers.local import LocalDocumentServiceFactory
from fluidframework_tpu.server.local_server import TpuLocalServer


def dashboard(server: TpuLocalServer, doc_ids) -> Dict[str, dict]:
    """One server-side pass: no containers, no replicas, no op replay."""
    seq = server.sequencer()
    out = {}
    for doc in doc_ids:
        out[doc] = {
            "body": seq.channel_text(doc, "default", "body"),
            "meta": (seq.channel_snapshot(doc, "default", "meta")
                     or {}).get("entries", {}),
            "edits": (seq.channel_snapshot(doc, "default", "edits")
                      or {}).get("counter", 0),
            "seq": seq.document_seq(doc),
        }
    return out


def main() -> None:
    server = TpuLocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))

    # A few live documents with concurrent editors.
    for doc in ("notes", "spec"):
        c = loader.create_detached(doc)
        ds = c.runtime.create_datastore("default")
        c.attach()
        body = ds.create_channel("body", SharedString.TYPE)
        meta = ds.create_channel("meta", SharedMap.TYPE)
        edits = ds.create_channel("edits", SharedCounter.TYPE)
        c2 = loader.resolve(doc)
        ds2 = c2.runtime.get_datastore("default")
        b2 = ds2.get_channel("body")

        body.insert_text(0, f"The {doc} document.")
        b2.insert_text(b2.get_length(), " More from a second editor.")
        meta.set("owner", "alice")
        ds2.get_channel("meta").set("status", "draft")
        edits.increment(2)
        ds2.get_channel("edits").increment(1)

    board = dashboard(server, ("notes", "spec"))
    for doc, row in board.items():
        print(f"[{doc}] seq={row['seq']} edits={row['edits']} "
              f"meta={row['meta']}")
        print(f"    {row['body']}")

    # The same lanes feed durable snapshots (cold-start load targets).
    shas = server.write_materialized_snapshots()
    print("materialized snapshot commits:", shas)


if __name__ == "__main__":
    main()
