"""Collaborative text editor (BASELINE config #2; reference
examples/data-objects/shared-text): a SharedString document with markers
(paragraph structure), annotations (formatting), interval collections
(comments), undo-redo, and the intelligence agent publishing analytics."""

from __future__ import annotations

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.framework.undo_redo import (
    SharedSegmentSequenceUndoRedoHandler, UndoRedoStackManager)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader


class SharedTextDocument(DataObject):
    def initializing_first_time(self):
        self.store.create_channel("text", SharedString.TYPE)
        self.store.create_channel("insights", SharedMap.TYPE)

    @property
    def text(self) -> SharedString:
        return self.store.get_channel("text")

    @property
    def insights(self) -> SharedMap:
        return self.store.get_channel("insights")

    # -- editing surface ---------------------------------------------------
    def insert(self, pos: int, content: str, props=None) -> None:
        self.text.insert_text(pos, content, props)

    def delete(self, start: int, end: int) -> None:
        self.text.remove_text(start, end)

    def bold(self, start: int, end: int) -> None:
        self.text.annotate_range(start, end, {"fontWeight": "bold"})

    def insert_paragraph(self, pos: int) -> None:
        self.text.insert_marker(pos, {"type": "paragraph"})

    def add_comment(self, start: int, end: int, comment: str):
        return self.text.get_interval_collection("comments").add(
            start, end, {"comment": comment})

    def comments(self):
        coll = self.text.get_interval_collection("comments")
        return [(coll.endpoints(iv), iv.properties["comment"])
                for iv in coll]

    def make_undo_stack(self) -> UndoRedoStackManager:
        manager = UndoRedoStackManager()
        SharedSegmentSequenceUndoRedoHandler(manager).attach(self.text)
        return manager

    def render(self):
        return self.text.get_text()


SharedTextFactory = DataObjectFactory("shared-text", SharedTextDocument)

CODE_DETAILS = {"package": "@examples/shared-text", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/shared-text", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(SharedTextFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def main() -> str:
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    loader = make_loader(LocalDocumentServiceFactory(server))
    c1 = loader.create_detached("shared-text-doc")
    c1.attach()
    c2 = loader.resolve("shared-text-doc")
    alice, bob = c1.request("/"), c2.request("/")
    alice.insert(0, "Collaborative editing on TPU.")
    bob.insert(0, "Hello! ")
    alice.bold(0, 6)
    bob.add_comment(7, 20, "love this part")
    assert alice.render() == bob.render()
    print(alice.render())
    return alice.render()


if __name__ == "__main__":
    main()
