"""Project tracker: nested JSON state in a SharedDirectory (BASELINE
config #4 — nested-subtree JSON merges with concurrent editors): projects
are subdirectories, tasks are keys inside them; concurrent editors merge
per-key (last-write-wins) while structural create/delete of subtrees
converges through the directory op protocol."""

from __future__ import annotations

from typing import Any, Dict, List

from fluidframework_tpu.dds.directory import SharedDirectory
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader


class ProjectTracker(DataObject):
    def initializing_first_time(self):
        self.store.create_channel("projects", SharedDirectory.TYPE)

    @property
    def directory(self) -> SharedDirectory:
        return self.store.get_channel("projects")

    # -- tracker surface ---------------------------------------------------
    def create_project(self, name: str, meta: Dict[str, Any] = None) -> None:
        sub = self.directory.create_sub_directory(name)
        sub.set("meta", dict(meta or {}))

    def delete_project(self, name: str) -> None:
        self.directory.root.delete_sub_directory(name)

    def projects(self) -> List[str]:
        return sorted(name for name, _ in
                      self.directory.root.subdirectories())

    def add_task(self, project: str, task_id: str, task: dict) -> None:
        sub = self.directory.get_working_directory(f"/{project}")
        sub.set(f"task:{task_id}", task)

    def set_status(self, project: str, task_id: str, status: str) -> None:
        sub = self.directory.get_working_directory(f"/{project}")
        task = dict(sub.get(f"task:{task_id}") or {})
        task["status"] = status
        sub.set(f"task:{task_id}", task)

    def tasks(self, project: str) -> Dict[str, dict]:
        sub = self.directory.get_working_directory(f"/{project}")
        if sub is None:
            return {}
        return {key[5:]: sub.get(key) for key in sub.keys()
                if key.startswith("task:")}

    def render(self):
        return {p: self.tasks(p) for p in self.projects()}


TrackerFactory = DataObjectFactory("project-tracker", ProjectTracker)

CODE_DETAILS = {"package": "@examples/project-tracker", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/project-tracker", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(TrackerFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def main():
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    loader = make_loader(LocalDocumentServiceFactory(server))
    c1 = loader.create_detached("tracker")
    c1.attach()
    c2 = loader.resolve("tracker")
    a, b = c1.request("/"), c2.request("/")
    a.create_project("tpu-port", {"owner": "alice"})
    b.add_task("tpu-port", "t1", {"title": "write kernels",
                                  "status": "open"})
    a.add_task("tpu-port", "t2", {"title": "bench", "status": "open"})
    b.set_status("tpu-port", "t1", "done")
    assert a.render() == b.render()
    print(a.render())
    return a.render()


if __name__ == "__main__":
    main()
