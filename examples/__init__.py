"""Example applications (reference examples/data-objects — the BASELINE
benchmark configs are drawn from these: clicker, collaborative text,
spreadsheet, nested JSON merges)."""
