"""Clicker: the hello-world data object (BASELINE config #1; reference
examples/data-objects/clicker): a SharedCounter behind a DataObject, every
client clicks, all replicas converge. This is the minimum end-to-end slice
through loader -> runtime -> DDS -> sequencer (SURVEY.md §7.5)."""

from __future__ import annotations

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader

COUNTER_KEY = "clicks"


class Clicker(DataObject):
    def initializing_first_time(self):
        counter = self.store.create_channel("counter", SharedCounter.TYPE)
        self.root.set(COUNTER_KEY, counter.handle.encode())

    @property
    def counter(self) -> SharedCounter:
        return self.store.get_channel("counter")

    def click(self, by: int = 1) -> None:
        self.counter.increment(by)

    @property
    def value(self) -> int:
        return self.counter.value

    def render(self):
        return f"clicks: {self.value}"


ClickerFactory = DataObjectFactory("clicker", Clicker)

CODE_DETAILS = {"package": "@examples/clicker", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/clicker", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(ClickerFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def main() -> int:
    """Run a small local session: three clients click concurrently."""
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    creator = make_loader(LocalDocumentServiceFactory(server))
    c0 = creator.create_detached("clicker-doc")
    c0.attach()
    clients = [c0] + [make_loader(LocalDocumentServiceFactory(server))
                      .resolve("clicker-doc") for _ in range(2)]
    clickers = [c.request("/") for c in clients]
    for i, clicker in enumerate(clickers):
        clicker.click(i + 1)
    values = [c.value for c in clickers]
    assert values == [6, 6, 6], values
    print(clickers[0].render())
    return values[0]


if __name__ == "__main__":
    main()
