"""Presence: live cursors over the transient signal stream.

Reference pattern: presence/cursor overlays ride ISignalMessage
(protocol-definitions/src/protocol.ts ISignalMessage; alfred submitSignal,
lambdas/src/alfred/index.ts:305-328) — transient broadcasts that bypass the
sequencer entirely: no seq numbers, no persistence, no catch-up. A client
that joins late sees only future cursor moves; one that disconnects
vanishes from the roster (audience removeMember).

The shared document itself (a SharedString note) rides the normal
sequenced stream — this example shows both streams side by side, which is
exactly how collaborative editors layer presence onto content.
"""

from __future__ import annotations

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader

CURSOR_SIGNAL = "cursor"


class PresenceNote(DataObject):
    """A shared note with live peer cursors.

    Sequenced state: the note text (SharedString "note").
    Transient state: self.cursors — {client_id: {"pos", "name"}} fed by
    datastore-scoped signals; pruned when the audience drops a member.
    """

    def initializing_first_time(self):
        note = self.store.create_channel("note", SharedString.TYPE)
        self.root.set("note", note.handle.encode())

    def has_initialized(self):
        self.cursors = {}
        self.store.on("signal", self._on_signal)
        audience = self.store.audience
        if audience is not None:
            audience.on("removeMember",
                        lambda cid: self.cursors.pop(cid, None))

    @property
    def note(self) -> SharedString:
        return self.store.get_channel("note")

    # -- presence ----------------------------------------------------------
    def move_cursor(self, pos: int, name: str) -> None:
        """Broadcast this client's cursor. Fire-and-forget: while
        disconnected the signal is dropped, not queued."""
        self.store.submit_signal(CURSOR_SIGNAL, {"pos": pos, "name": name})

    def _on_signal(self, signal_type, content, local, client_id) -> None:
        if signal_type != CURSOR_SIGNAL or local:
            return
        self.cursors[client_id] = dict(content)

    def render(self) -> str:
        peers = ", ".join(
            f"{c['name']}@{c['pos']}" for c in self.cursors.values())
        return f"note: {self.note.get_text()!r} | peers: {peers or '-'}"


PresenceFactory = DataObjectFactory("presence-note", PresenceNote)

CODE_DETAILS = {"package": "@examples/presence", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/presence", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(PresenceFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def main() -> str:
    """Two editors type into the note and wave cursors at each other."""
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    creator = make_loader(LocalDocumentServiceFactory(server))
    c0 = creator.create_detached("presence-doc")
    c0.attach()
    c1 = make_loader(LocalDocumentServiceFactory(server)) \
        .resolve("presence-doc")
    alice, bob = c0.request("/"), c1.request("/")

    alice.note.insert_text(0, "hello")
    alice.move_cursor(5, "alice")
    bob.move_cursor(0, "bob")

    assert bob.note.get_text() == "hello"
    assert bob.cursors and next(iter(bob.cursors.values()))["name"] == "alice"
    assert alice.cursors and next(iter(alice.cursors.values()))["name"] == "bob"
    out = bob.render()
    print(out)
    return out


if __name__ == "__main__":
    main()
