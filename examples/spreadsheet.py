"""Spreadsheet over SharedMatrix (BASELINE config #3; reference
examples/data-objects/table-document): cells in a SharedMatrix, concurrent
row/col insertion, formula cells (=SUM ranges) evaluated on read."""

from __future__ import annotations

import re
from typing import Any, Optional

from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader

_FORMULA = re.compile(
    r"^=SUM\((?P<r1>\d+),(?P<c1>\d+):(?P<r2>\d+),(?P<c2>\d+)\)$")


class Spreadsheet(DataObject):
    def initializing_first_time(self):
        matrix = self.store.create_channel("cells", SharedMatrix.TYPE)
        matrix.insert_rows(0, 4)
        matrix.insert_cols(0, 4)

    @property
    def matrix(self) -> SharedMatrix:
        return self.store.get_channel("cells")

    # -- table surface (reference table-document API shape) ----------------
    @property
    def num_rows(self) -> int:
        return self.matrix.row_count

    @property
    def num_cols(self) -> int:
        return self.matrix.col_count

    def set_cell(self, row: int, col: int, value: Any) -> None:
        self.matrix.set_cell(row, col, value)

    def get_cell(self, row: int, col: int) -> Any:
        return self.matrix.get_cell(row, col)

    def insert_rows(self, at: int, count: int) -> None:
        self.matrix.insert_rows(at, count)

    def insert_cols(self, at: int, count: int) -> None:
        self.matrix.insert_cols(at, count)

    def remove_rows(self, at: int, count: int) -> None:
        self.matrix.remove_rows(at, count)

    def evaluate(self, row: int, col: int) -> Any:
        """Formula-aware read: \"=SUM(r1,c1:r2,c2)\" sums the inclusive
        range, skipping blanks/non-numbers (table-document's evaluation
        role)."""
        value = self.get_cell(row, col)
        if not isinstance(value, str):
            return value
        m = _FORMULA.match(value)
        if not m:
            return value
        total = 0
        for r in range(int(m["r1"]), int(m["r2"]) + 1):
            for c in range(int(m["c1"]), int(m["c2"]) + 1):
                cell = self.get_cell(r, c)
                if isinstance(cell, (int, float)):
                    total += cell
        return total

    def render(self):
        return [[self.evaluate(r, c) for c in range(self.num_cols)]
                for r in range(self.num_rows)]


SpreadsheetFactory = DataObjectFactory("spreadsheet", Spreadsheet)

CODE_DETAILS = {"package": "@examples/spreadsheet", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/spreadsheet", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(SpreadsheetFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def main():
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    loader = make_loader(LocalDocumentServiceFactory(server))
    c1 = loader.create_detached("sheet")
    c1.attach()
    c2 = loader.resolve("sheet")
    a, b = c1.request("/"), c2.request("/")
    a.set_cell(0, 0, 10)
    b.set_cell(0, 1, 32)
    a.set_cell(1, 0, "=SUM(0,0:0,3)")
    assert a.evaluate(1, 0) == b.evaluate(1, 0) == 42
    print("sum:", a.evaluate(1, 0))
    return a.evaluate(1, 0)


if __name__ == "__main__":
    main()
