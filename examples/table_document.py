"""TableDocument: SharedMatrix cells composed with SEQUENCE-backed axes
(reference examples/data-objects/table-document/src/document.ts:34 —
SparseMatrix + SharedNumberSequence rows/cols + interval cell ranges).

The composition is the point: row/col structure changes touch BOTH the
matrix (permutation runs) and the axis sequences (merge-tree items) in one
logical edit, axis annotations ride merge-tree annotate sweeps, and named
cell ranges anchor to interval collections on the row axis so they slide
with concurrent structural churn — three DDS engines converging together
(chaos-farm coverage in tests/test_table_document.py)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.dds.sequence import SharedNumberSequence
from fluidframework_tpu.framework.container_factories import (
    ContainerRuntimeFactoryWithDefaultDataStore)
from fluidframework_tpu.framework.data_object import (DataObject,
                                                      DataObjectFactory)
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.loader.container import Loader

TABLE_DOCUMENT_TYPE = "@fluid-example/table-document"


class TableDocument(DataObject):
    """Cells + row axis + col axis, edited as one table."""

    def initializing_first_time(self):
        self.store.create_channel("matrix", SharedMatrix.TYPE)
        self.store.create_channel("rows", SharedNumberSequence.TYPE)
        self.store.create_channel("cols", SharedNumberSequence.TYPE)

    # -- channels ----------------------------------------------------------
    @property
    def matrix(self) -> SharedMatrix:
        return self.store.get_channel("matrix")

    @property
    def rows(self) -> SharedNumberSequence:
        return self.store.get_channel("rows")

    @property
    def cols(self) -> SharedNumberSequence:
        return self.store.get_channel("cols")

    @property
    def num_rows(self) -> int:
        return self.rows.get_item_count()

    @property
    def num_cols(self) -> int:
        return self.cols.get_item_count()

    # -- structure: matrix AND axis move together (document.ts:120-139) ---
    def insert_rows(self, at: int, count: int) -> None:
        self.matrix.insert_rows(at, count)
        self.rows.insert_range(at, [0] * count)

    def remove_rows(self, at: int, count: int) -> None:
        self.matrix.remove_rows(at, count)
        self.rows.remove_range(at, at + count)

    def insert_cols(self, at: int, count: int) -> None:
        self.matrix.insert_cols(at, count)
        self.cols.insert_range(at, [0] * count)

    def remove_cols(self, at: int, count: int) -> None:
        self.matrix.remove_cols(at, count)
        self.cols.remove_range(at, at + count)

    # -- cells -------------------------------------------------------------
    def set_cell(self, row: int, col: int, value: Any) -> None:
        self.matrix.set_cell(row, col, value)

    def get_cell(self, row: int, col: int) -> Any:
        return self.matrix.get_cell(row, col)

    def extract(self) -> List[List[Any]]:
        return self.matrix.extract()

    # -- axis annotations (document.ts:87-101) -----------------------------
    def annotate_rows(self, start: int, end: int, props: dict) -> None:
        self.rows.annotate_range(start, end, props)

    def annotate_cols(self, start: int, end: int, props: dict) -> None:
        self.cols.annotate_range(start, end, props)

    @staticmethod
    def _axis_props(seq: SharedNumberSequence, index: int) -> dict:
        from fluidframework_tpu.mergetree.oracle import Items
        tree = seq.client.tree
        acc = 0
        for seg in tree.segments:
            vlen = tree.visible_length(seg, tree.current_seq,
                                       seq.client.client_id)
            if vlen <= 0:
                continue
            if acc <= index < acc + vlen and isinstance(seg.text, Items):
                return dict(seg.props) if seg.props else {}
            acc += vlen
        return {}

    def get_row_properties(self, row: int) -> dict:
        return self._axis_props(self.rows, row)

    def get_col_properties(self, col: int) -> dict:
        return self._axis_props(self.cols, col)

    # -- named row ranges: intervals on the row axis slide with churn
    #    (document.ts:111-117 createInterval over the matrix position
    #    space; here anchored on the row sequence) -------------------------
    def create_range(self, label: str, start_row: int, end_row: int) -> None:
        self.rows.get_interval_collection("ranges").add(
            start_row, end_row, {"label": label})

    def resolve_range(self, label: str) -> Optional[Tuple[int, int]]:
        coll = self.rows.get_interval_collection("ranges")
        for iv in coll:
            if (iv.properties or {}).get("label") == label:
                return coll.endpoints(iv)
        return None


TableDocumentFactory = DataObjectFactory(TABLE_DOCUMENT_TYPE, TableDocument)

CODE_DETAILS = {"package": "@examples/table-document", "version": "^1.0.0"}


def make_loader(service_factory) -> Loader:
    code_loader = CodeLoader()
    code_loader.register(
        "@examples/table-document", "1.0.0",
        ContainerRuntimeFactoryWithDefaultDataStore(TableDocumentFactory))
    return Loader(service_factory, code_loader=code_loader,
                  code_details=CODE_DETAILS)


def demo() -> dict:
    """Two clients edit one table concurrently through a local service."""
    from fluidframework_tpu.loader.drivers.local import (
        LocalDocumentServiceFactory)
    from fluidframework_tpu.server.local_server import LocalServer

    server = LocalServer()
    loader = make_loader(LocalDocumentServiceFactory(server))
    c1 = loader.create_detached("table")
    t1 = c1.request("/")
    t1.insert_rows(0, 3)
    t1.insert_cols(0, 3)
    t1.set_cell(0, 0, "Q1")
    t1.set_cell(1, 1, 42)
    c1.attach()

    c2 = make_loader(LocalDocumentServiceFactory(server)).resolve("table")
    t2 = c2.request("/")
    t2.insert_rows(1, 1)  # concurrent structural edit
    t1.annotate_rows(0, 1, {"header": True})
    t1.create_range("totals", 1, 3)
    t2.set_cell(3, 2, "sum")

    assert t1.extract() == t2.extract()
    assert t1.num_rows == t2.num_rows == 4
    return {"rows": t1.num_rows, "cols": t1.num_cols,
            "grid": t1.extract(),
            "row0": t1.get_row_properties(0),
            "totals": t1.resolve_range("totals")}


if __name__ == "__main__":
    print(demo())
