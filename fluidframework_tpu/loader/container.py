"""Container + Loader: document lifecycle against a real service.

Capability parity with reference container-loader/src/{loader.ts,
container.ts:186,543}: create-detached -> attach (upload initial summary,
connect), load (fetch summary, init protocol + runtime, connect, process op
tail), reconnect with pending resubmission, quorum/audience tracking, and
the client summarize path (upload summary -> summarize op -> scribe ack,
reference summaryCollection.ts:244 waitSummaryAck).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..core.events import TypedEventEmitter
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..protocol.protocol_handler import ProtocolOpHandler, ProtocolState
from ..protocol.summary import SummaryTree
from ..runtime.container_runtime import ContainerRuntime
from ..runtime.datastore_runtime import ChannelRegistry
from .delta_manager import DeltaManager
from .drivers.base import IDocumentService, IDocumentServiceFactory


class Audience(TypedEventEmitter):
    """Connected-client roster (reference container-loader/src/audience.ts)."""

    def __init__(self):
        super().__init__()
        self.members: Dict[str, dict] = {}

    def add_member(self, client_id: str, details: dict) -> None:
        self.members[client_id] = details
        self.emit("addMember", client_id, details)

    def remove_member(self, client_id: str) -> None:
        if client_id in self.members:
            del self.members[client_id]
            self.emit("removeMember", client_id)


class Container(TypedEventEmitter):
    """Events: "connected", "disconnected", "op", "summaryAck",
    "summaryNack", "signal" (SignalMessage, local) — transient messages
    outside the sequenced stream — and "closed"."""

    def __init__(self, document_id: str, service: IDocumentService,
                 registry: Optional[ChannelRegistry] = None,
                 code_loader=None,
                 client_details: Optional[dict] = None):
        super().__init__()
        self.document_id = document_id
        self.service = service
        self.client_details = dict(client_details or {})
        self.read_only = self.client_details.get("mode") == "read"
        self.storage = service.connect_to_storage()
        self.delta_manager = DeltaManager(service, self.client_details)
        self.protocol = ProtocolOpHandler()
        self.audience = Audience()
        self.runtime = ContainerRuntime(registry=registry)
        self.runtime.audience = self.audience
        self.attached = False
        self.connected = False
        self.closed = False
        self.code_loader = code_loader
        self.runtime_factory = None  # set when code details resolve
        self._code_details: Optional[dict] = None
        self._last_summary_handle: Optional[str] = None
        self._summary_waiters: List[Callable[[str, bool, Any], None]] = []
        import threading as _threading
        self._nack_gate = _threading.Lock()
        self._nack_recovery_live = False
        self._nack_rearm = False  # throttle nack landed mid-recovery
        self._nack_rearm_after = None

    @property
    def op_lock(self):
        """The container's serialization lock (the JS-event-loop analog).
        Network drivers deliver inbound ops on a reader thread under this
        lock; application code mutating DDSes from its own threads wraps the
        mutation in `with container.op_lock:` to serialize against them."""
        return self.delta_manager.lock

    # -- creation / loading ------------------------------------------------
    @staticmethod
    def create_detached(document_id: str, service: IDocumentService,
                        registry: Optional[ChannelRegistry] = None,
                        code_loader=None,
                        code_details: Optional[dict] = None) -> "Container":
        container = Container(document_id, service, registry, code_loader)
        if code_details is not None:
            container.set_code_details(code_details)
        return container

    @staticmethod
    def load(document_id: str, service: IDocumentService,
             registry: Optional[ChannelRegistry] = None,
             code_loader=None,
             client_details: Optional[dict] = None) -> "Container":
        """Reference Container.load (container.ts:186): summary + op tail
        — with the read tier's fast path layered on top
        (docs/read_path.md): the storage round trip returns `summary +
        catch-up delta` together, the delta adopts the summary-to-head
        gap as a state swap, and connect() then replays only the residue
        past the artifact's seq instead of the whole tail. An absent,
        stale, or unadoptable artifact degrades to exactly the old
        summary + tail-replay behavior.

        client_details={"mode": "read"} loads a READ-ONLY observer: it
        follows the live op/signal streams but never joins the quorum,
        never holds back the MSN, and never submits."""
        container = Container(document_id, service, registry, code_loader,
                              client_details)
        try:
            summary, artifact = container.storage.get_catchup()
        except Exception:  # noqa: BLE001 — a dead read tier must not fail loads
            from ..telemetry.counters import record_swallow
            record_swallow("container.get_catchup")
            summary, artifact = container.storage.get_summary(), None
        if summary is None:
            raise FileNotFoundError(f"document {document_id!r} has no summary")
        container._load_from_summary(summary)
        versions = container.storage.get_versions(1)
        container._last_summary_handle = versions[0] if versions else None
        if artifact is not None:
            container._try_adopt_catchup(artifact)
        container.attached = True
        container._instantiate_code(existing=True)
        container.connect()
        return container

    # -- code loading (web-code-loader + quorum "code" proposal) -----------
    def set_code_details(self, details: dict) -> None:
        """Select the code package for a detached container. The accepted
        proposal is folded into the quorum pre-attach (the reference
        serializes protocol state with the code proposal approved for
        detached containers) and the runtime factory runs first-time
        initialization."""
        if self.attached:
            raise RuntimeError("use propose_code_details on live containers")
        self._code_details = details
        self.protocol.quorum.add_proposal("code", details, 0)
        self.protocol.quorum.update_minimum_sequence_number(0)
        self._instantiate_code(existing=False)

    def _instantiate_code(self, existing: bool) -> None:
        if self.code_loader is None:
            return
        details = self.protocol.quorum.get("code") or self._code_details
        if details is None:
            return
        module = self.code_loader.load(details)
        self.runtime_factory = module.fluid_export
        self.runtime_factory.initialize(self, existing)

    def propose_code_details(self, details: dict) -> None:
        """Live code upgrade: a quorum "code" proposal (container.ts code
        upgrade path). When the MSN passes it unrejected, "codeChanged"
        fires; hosts reload the container against the new module (the
        reference closes + reloads the context the same way)."""
        self.delta_manager.submit(
            MessageType.PROPOSE, {"key": "code", "value": details})

    def request(self, url: str = "/"):
        """Route a request through the code-loaded runtime factory
        (reference request handler chain / base-host requestFluidObject)."""
        if self.runtime_factory is None:
            raise RuntimeError("container has no code-loaded runtime factory")
        return self.runtime_factory.request(self, url)

    def _load_from_summary(self, summary: SummaryTree) -> None:
        protocol_blob = summary.entries.get(".protocol")
        if protocol_blob is not None:
            state = json.loads(protocol_blob.content)
            self.protocol = ProtocolOpHandler.load(ProtocolState(
                sequence_number=state["sequenceNumber"],
                minimum_sequence_number=state["minimumSequenceNumber"],
                quorum_snapshot=state["quorum"]))
        self.runtime.load(summary.entries[".app"])

    # -- read-path catch-up adoption (docs/read_path.md) -------------------
    def _plan_catchup_adoption(self, artifact: dict):
        """Validate an artifact against the container's current state and
        return the fully-decoded adoption plan, or None with the fallback
        counter bumped. NOTHING mutates here — adoption is all-or-nothing
        (a partial adoption would desync channels against the shared
        per-doc sequence bookkeeping)."""
        from ..server.readpath import (quorum_ordinals,
                                       translate_entry_clients,
                                       unpack_entries_narrow)
        from ..telemetry.counters import increment

        seq = int(artifact["seq"])
        if seq <= self.protocol.sequence_number:
            # The summary we loaded already covers the artifact's state
            # (a client summary landed after the last refresh).
            increment("catchup.client.stale_artifact")
            return None
        # wire client id -> quorum ordinal (its join seq), from the SAME
        # quorum snapshot the adoption installs — protocol state and
        # perspective math cannot disagree.
        members = quorum_ordinals(artifact["quorum"])
        idx_to_ordinal = {}
        for i, cid in enumerate(artifact.get("clients", [])):
            if cid in members:
                idx_to_ordinal[i] = members[cid]
            else:
                # A DEPARTED client: its identity is semantically inert —
                # no op of its can ever arrive again (client ids are
                # never reused), so contended rows it left behind only
                # need an ordinal that collides with no live client
                # (join seqs are >= 1) and no future one. Unique
                # negatives below -1 satisfy both; the scalar replay
                # path keeps the real historical join seq here, a
                # divergence confined to metadata that can never affect
                # visibility again (docs/read_path.md).
                idx_to_ordinal[i] = -(i + 2)
        plan = []
        try:
            for store_id, channel_id, header, blob in artifact["channels"]:
                store = self.runtime.datastores.get(store_id)
                channel = store.channels.get(channel_id) \
                    if store is not None else None
                if channel is None \
                        or not hasattr(channel, "adopt_catchup_core") \
                        or not channel.can_adopt_catchup():
                    increment("catchup.client.unadoptable")
                    return None
                entries = unpack_entries_narrow(blob)
                # KeyError here = a contended row references a client the
                # quorum no longer knows: untranslatable, fall back.
                entries = translate_entry_clients(entries, idx_to_ordinal)
                plan.append((channel, entries, header))
        except (KeyError, ValueError, TypeError):
            increment("catchup.client.undecodable")
            return None
        return seq, members, plan

    def _try_adopt_catchup(self, artifact: dict) -> bool:
        """Adopt a catch-up artifact: protocol state + every channel swap
        to the artifact's seq, so the tail replay that follows covers
        only the residue past it. Returns False (state untouched) on any
        validation failure — the tail replay fallback is always
        correct, just O(tail)."""
        from ..telemetry.counters import increment

        planned = self._plan_catchup_adoption(artifact)
        if planned is None:
            return False
        seq, members, plan = planned
        msn = int(artifact.get("msn", 0))
        self.protocol = ProtocolOpHandler.load(ProtocolState(
            sequence_number=seq, minimum_sequence_number=msn,
            quorum_snapshot=artifact["quorum"]))
        self.runtime.sequence_number = seq
        self.runtime.minimum_sequence_number = msn
        # Ordinal table + audience come from the quorum snapshot — the
        # tail's join/leave ops we skipped are folded into it.
        self.runtime._ordinals = dict(members)
        details = {cid: (m.get("details") or {})
                   for cid, m in artifact["quorum"].get("members", [])}
        for cid in members:
            if cid not in self.audience.members:
                self.audience.add_member(cid, details.get(cid, {}))
        for channel, entries, header in plan:
            channel.adopt_catchup_core(
                entries,
                seq=int(header.get("sequenceNumber", seq)),
                min_seq=int(header.get("minimumSequenceNumber", 0)),
                total_length=int(header.get("totalLength", 0)))
        increment("catchup.client.adopted")
        self.emit("catchUpAdopted", seq)
        return True

    def _reconnect_catchup(self, last_seq: int):
        """DeltaManager catch-up hook: on (re)connect with a long gap, a
        clean container (no pending local state) fetches the artifact
        and adopts instead of replaying the gap. Returns the adopted seq
        (the delta manager resumes the residue there) or None."""
        from ..telemetry.counters import increment

        dm = self.delta_manager
        if self.runtime.pending.count:
            return None  # unacked local ops need scalar ack pairing
        if last_seq < self.protocol.sequence_number:
            return None  # mid-load inconsistency: let the replay settle it
        try:
            artifact = self.storage.get_catchup_artifact()
        except Exception:  # noqa: BLE001 — dead read tier: replay instead
            increment("catchup.client.fetch_failed")
            return None
        if artifact is None:
            return None
        gap = int(artifact.get("seq", 0)) - last_seq
        if gap < dm.bulk_catchup_threshold:
            return None  # short residue: the ordinary replay is cheaper
        with dm.lock:
            # Revalidate under the lock: the reader thread may have
            # delivered ops (or the runtime submitted) since the probe.
            if self.runtime.pending.count \
                    or dm.last_sequence_number != last_seq:
                return None
            if not self._try_adopt_catchup(artifact):
                return None
            dm.last_sequence_number = self.protocol.sequence_number
            dm.minimum_sequence_number = \
                self.protocol.minimum_sequence_number
            increment("catchup.client.reconnect_adopted")
            return self.protocol.sequence_number

    # -- attach (detached -> live) ----------------------------------------
    def attach(self) -> None:
        """Upload the initial summary and go live (container.ts:543)."""
        if self.attached:
            return
        for store in self.runtime.datastores.values():
            store.connect()
        handle = self.storage.upload_summary(self._assemble_summary(),
                                             initial=True)
        # The attach summary IS the ref head and exactly this state: it is
        # the incremental baseline from the very first client summary.
        self._last_summary_handle = handle
        self.runtime.baseline_epochs()
        self.attached = True
        self.connect()

    # -- connection --------------------------------------------------------
    def connect(self) -> None:
        self.protocol.quorum.on("approveProposal", self._on_approve_proposal)
        self.delta_manager.attach_op_handler(
            self.protocol.sequence_number, self._process)
        self.delta_manager.attach_bulk_handler(self._process_bulk)
        self.delta_manager.attach_catchup_fetch(self._reconnect_catchup)
        self.delta_manager.on("disconnect", self._on_disconnect)
        self.delta_manager.on("nack", self._on_nack)
        self.delta_manager.on("connect", self._on_connect_identity)
        self.delta_manager.on("signal", self._process_signal)
        self.delta_manager.connect()

    def _on_connect_identity(self, client_id: str) -> None:
        """Runs before the op pump: the runtime must know its wire identity
        when its own join op arrives (that is what flips it connected)."""
        self.runtime.set_local_client(client_id)
        if not self.runtime.attached:
            self.runtime.attach(self.delta_manager.submit)
        else:
            self.runtime._submit_fn = self.delta_manager.submit
        self.runtime._submit_signal_fn = self.delta_manager.submit_signal
        self.runtime._submit_batch_fn = self.delta_manager.submit_batch
        self.runtime.signals_live = True
        if self.read_only:
            # No join op will ever arrive for us, so the runtime never
            # goes connected; local edits RAISE at the runtime boundary
            # (an optimistic edit that can never ack would shadow remote
            # state forever), while ops and signals flow in. The container
            # itself reports connected immediately.
            self.runtime.read_only = True
            self.connected = True
            self.emit("connected")

    def _on_approve_proposal(self, seq, key, value, msn) -> None:
        if key == "code":
            self.emit("codeChanged", value)

    def _on_disconnect(self) -> None:
        self.connected = False
        self.runtime.set_connected(False)
        self.emit("disconnected")

    def _on_nack(self, nack) -> None:
        """Nack dispatch (reference deltaManager: retryable -> resubmit,
        non-retryable -> close):
        - 413 (too large): resubmitting the identical op can never
          succeed — close the container with an "error" event instead of
          reconnect-looping forever.
        - 429 (throttled): honor retryAfter on a WORKER thread — the nack
          can arrive synchronously inside submit with the container lock
          held, and sleeping there would stall every other thread.
        - anything else: immediate reconnect + resubmit."""
        from ..protocol.messages import (NACK_SERVICE_UNAVAILABLE,
                                         NACK_THROTTLED, NACK_TOO_LARGE)
        content = getattr(nack, "content", None)
        code = getattr(content, "code", None)
        if code == NACK_TOO_LARGE:
            self.emit("error", nack)
            self.close()
            return
        if code in (NACK_THROTTLED, NACK_SERVICE_UNAVAILABLE):
            # 503 is the admission controller's DEGRADE refusal
            # (server/admission.py): same contract as 429 — honor the
            # server-computed retry_after; an immediate reconnect storm
            # is exactly what a degraded server cannot absorb.
            #
            # Quiesce SYNCHRONOUSLY before the backoff sleep: the nacked
            # op is still at the head of the pending queue, and leaving
            # the connection up while the worker waits lets later edits
            # submit — a later op admitted past the refilled bucket acks
            # out of order against that pending head (DataCorruption).
            # Dropping the connection here archives in-flight ops and
            # parks new edits locally until the recovery reconnects.
            # (delta_manager.disconnect takes no lock, and the nack can
            # arrive on this thread inside submit under the RLock.)
            self._on_disconnect()
            self.delta_manager.disconnect()
            with self._nack_gate:
                if self._nack_recovery_live:
                    # One recovery in flight absorbs the storm — but the
                    # resubmission itself may be what got nacked, so
                    # re-arm: the recovery loop runs another round after
                    # its reconnect instead of losing the wakeup.
                    self._nack_rearm = True
                    self._nack_rearm_after = getattr(
                        content, "retry_after_s", None)
                    return
                self._nack_recovery_live = True
            import threading as _threading
            _threading.Thread(
                target=self._throttle_recover,
                args=(getattr(content, "retry_after_s", None),),
                daemon=True).start()
            return
        self.reconnect()

    def _throttle_recover(self, retry_after) -> None:
        import time as _time
        while True:
            try:
                if retry_after:
                    _time.sleep(min(float(retry_after), 5.0))
                if not self.closed:
                    self.reconnect()
            except BaseException:
                # The recovery thread is dying: release the gate so a
                # future nack can start a fresh recovery (a stuck True
                # would silence throttle recovery forever).
                with self._nack_gate:
                    self._nack_recovery_live = False
                    self._nack_rearm = False
                    self._nack_rearm_after = None
                raise
            with self._nack_gate:
                rearmed = self._nack_rearm and not self.closed
                # Server gave no retryAfter: floor the re-arm backoff at
                # 1s — a 429 path must never tight-loop the server.
                retry_after = self._nack_rearm_after or 1.0
                self._nack_rearm = False
                self._nack_rearm_after = None
                if not rearmed:
                    self._nack_recovery_live = False
                    return

    def reconnect(self) -> None:
        self._on_disconnect()
        self.delta_manager.reconnect()

    def close(self) -> None:
        self.closed = True
        self.delta_manager.disconnect()
        self.emit("closed")

    # -- inbound sequenced stream -----------------------------------------
    def _process(self, message: SequencedDocumentMessage) -> None:
        self.protocol.process_message(message)
        mtype = message.type
        if mtype == MessageType.CLIENT_JOIN:
            detail = json.loads(message.data) if message.data else {}
            joined = detail.get("clientId")
            self.audience.add_member(joined, detail.get("detail", {}))
            if joined == self.delta_manager.client_id:
                self.connected = True
                self.emit("connected")
        elif mtype == MessageType.CLIENT_LEAVE:
            detail = json.loads(message.data) if message.data else {}
            self.audience.remove_member(detail.get("clientId"))
        elif mtype == MessageType.SUMMARIZE:
            # Our own summarize op sequencing: its sequence number is the
            # summarySequenceNumber acks correlate on (summaryCollection.ts).
            if message.client_id == self.delta_manager.client_id:
                for waiter in self._summary_waiters:
                    if waiter["csn"] == message.client_sequence_number:
                        waiter["summary_seq"] = message.sequence_number
        elif mtype == MessageType.SUMMARY_ACK:
            self._last_summary_handle = message.contents["handle"]
            # The acked upload's epochs become the incremental baseline.
            self.runtime.on_summary_ack(message.contents["handle"])
            self._notify_summary(True, message.contents)
            self.emit("summaryAck", message.contents)
        elif mtype == MessageType.SUMMARY_NACK:
            self._notify_summary(False, message.contents)
            self.emit("summaryNack", message.contents)
        self.runtime.process(message)
        self.emit("op", message)

    # -- signals (transient stream) ----------------------------------------
    def submit_signal(self, signal_type: str, content: Any) -> None:
        """Broadcast a container-scope transient signal (reference
        container.ts submitSignal). Delivery is best-effort: unsequenced,
        undurable, client-relative order only."""
        self.runtime.submit_signal(signal_type, content)

    def _process_signal(self, signal) -> None:
        local = signal.client_id is not None and \
            signal.client_id == self.delta_manager.client_id
        self.runtime.process_signal(signal, local)
        self.emit("signal", signal, local)

    def _process_bulk(self, tail) -> None:
        """Catch-up tail processing with the device fast path.

        Ops on DIFFERENT channels commute (channel isolation), so the tail
        partitions into per-channel buffers that accumulate across
        interleavings — a document whose history alternates between two
        channels still reaches the bulk threshold on each (a contiguity
        requirement never would: real docs interleave every channel).
        Protocol bookkeeping stays strictly in tail order (buffered ops
        process protocol-side at buffer time). Any scalar-processed
        message except a heartbeat is a runtime-visible boundary
        (self-join ordinal adoption, client_left hooks, own-op acks on a
        buffered channel): all buffers flush before it so runtime-level
        ordering is preserved. Per-op events coalesce into one
        "bulkCatchUp" delta per channel, the reference's deferred-ops
        load behavior (sequence.ts:664)."""
        from ..core.errors import BulkApplyUnsupported

        buffers: dict = {}  # key -> [msgs]; insertion order = first seen
        hi_seq = [0, 0]  # highest (seq, msn) applied via a bulk buffer

        def flush() -> None:
            threshold = self.delta_manager.bulk_catchup_threshold
            # Messages the walk already applied scalar (joins, noops) may
            # sit PAST the buffered seqs: never let the restore below
            # regress what runtime.process already advanced to.
            hi_seq[0] = max(hi_seq[0], self.runtime.sequence_number)
            hi_seq[1] = max(hi_seq[1],
                            self.runtime.minimum_sequence_number)
            scalar_msgs = []
            for msgs in buffers.values():
                done = False
                if len(msgs) >= threshold:
                    try:
                        self.runtime.process_channel_bulk(msgs)
                        done = True
                    except (BulkApplyUnsupported, ValueError):
                        done = False  # state untouched: scalar fallback
                if not done:
                    scalar_msgs.extend(msgs)
                hi_seq[0] = max(hi_seq[0], msgs[-1].sequence_number)
                hi_seq[1] = max(hi_seq[1],
                                msgs[-1].minimum_sequence_number)
            buffers.clear()
            # Fallback buffers replay in GLOBAL sequence order (channel
            # isolation makes any order state-safe, but "op" listeners —
            # last_edited, summarizer — expect monotonic seqs). Protocol
            # side already ran at buffer time: runtime half only.
            scalar_msgs.sort(key=lambda m: m.sequence_number)
            for m in scalar_msgs:
                self.runtime.process(m)
                self.emit("op", m)
            # Bulk bypasses runtime.process (and scalar replay may end on
            # an earlier-seq buffer): pin the post-flush bookkeeping to
            # the true high-water mark — a summarize right after catch-up
            # stamps these into .metadata.
            if hi_seq[0] > self.runtime.sequence_number:
                self.runtime.sequence_number = hi_seq[0]
            if hi_seq[1] > self.runtime.minimum_sequence_number:
                self.runtime.minimum_sequence_number = hi_seq[1]

        dm = self.delta_manager
        for msg in tail:
            key = self._bulk_key(msg)
            if key is not None:
                self.protocol.process_message(msg)
                buffers.setdefault(key, []).append(msg)
                continue
            if msg.type != MessageType.NO_OP and buffers:
                flush()
            # Keep the delta manager's position current at every scalar
            # boundary: a resubmission triggered INSIDE this message
            # (self-join -> _resubmit_all) stamps refSeq from
            # last_sequence_number, and the bulk path otherwise only
            # advances it after the WHOLE tail — a pre-gap refSeq below
            # the server's MSN gets nacked, and the nack's reconnect
            # re-enters this very path: an unbounded synchronous
            # recursion (surfaced by the read-tier reconnect tests).
            if msg.sequence_number > dm.last_sequence_number:
                dm.last_sequence_number = msg.sequence_number
            if msg.minimum_sequence_number > dm.minimum_sequence_number:
                dm.minimum_sequence_number = msg.minimum_sequence_number
            self._process(msg)
        flush()

    def _bulk_key(self, message) -> tuple | None:
        """(store, channel) when the message can join a device bulk run."""
        if message.type != MessageType.OPERATION:
            return None
        if message.client_id == self.delta_manager.client_id:
            return None  # local acks need pending-state pairing
        if self.runtime.pending.has_prior(message.client_id):
            return None  # ours under a previous connection id: same
        contents = message.contents
        if not isinstance(contents, dict) or "attachStore" in contents:
            return None
        envelope = contents.get("contents")
        if not isinstance(envelope, dict):
            return None
        return self.runtime.bulk_route(contents.get("address"),
                                       envelope.get("address"),
                                       message.client_id)

    # -- summaries ---------------------------------------------------------
    def _assemble_summary(self, incremental: bool = False) -> SummaryTree:
        root = SummaryTree()
        snap = self.protocol.snapshot()
        root.add_blob(".protocol", json.dumps({
            "sequenceNumber": snap.sequence_number,
            "minimumSequenceNumber": snap.minimum_sequence_number,
            "quorum": snap.quorum_snapshot,
        }))
        root.entries[".app"] = self.runtime.summarize(
            incremental=incremental)
        return root

    def summarize(self, on_result: Optional[Callable[[str, bool, Any], None]]
                  = None) -> str:
        """Client summarize: upload -> summarize op -> scribe ack
        (SURVEY.md §3.5). Returns the uploaded commit handle.

        Incremental when a parent summary exists: channels (and whole
        datastores) unchanged since the last ACKED summary serialize as
        SummaryHandles the storage layer resolves against the parent
        commit — only deltas upload (reference trackState/SummaryTracker,
        sharedObject.ts:210-244, containerRuntime.ts:1317-1383)."""
        # Capture epochs BEFORE assembly: ops racing the (possibly slow,
        # network) upload bump past this snapshot and re-upload next time.
        epochs = self.runtime.all_channel_epochs()
        handle = self.storage.upload_summary(
            self._assemble_summary(
                incremental=self._last_summary_handle is not None),
            parent=self._last_summary_handle)
        self.runtime.record_upload(handle, epochs)
        # Register the waiter inside before_send: over an in-process service
        # the sequenced SUMMARIZE op AND its ack can both arrive synchronously
        # within submit(), and the waiter must exist (with its csn) by then.
        waiter = ({"csn": None, "summary_seq": None, "fn": on_result}
                  if on_result is not None else None)

        def _register(csn: int) -> None:
            if waiter is not None:
                waiter["csn"] = csn
                self._summary_waiters.append(waiter)

        self.delta_manager.submit(MessageType.SUMMARIZE, {
            "handle": handle,
            "head": self._last_summary_handle,
            "message": f"summary@{self.protocol.sequence_number}",
        }, before_send=_register)
        return handle

    def _notify_summary(self, ack: bool, contents: Any) -> None:
        proposal = (contents or {}).get("summaryProposal", {})
        target = proposal.get("summarySequenceNumber")
        remaining = []
        for waiter in self._summary_waiters:
            if waiter["summary_seq"] == target and target is not None:
                waiter["fn"](contents.get("handle"), ack, contents)
            else:
                remaining.append(waiter)
        self._summary_waiters = remaining


class Loader:
    """Resolves document ids to Containers (reference loader.ts)."""

    def __init__(self, factory: IDocumentServiceFactory,
                 registry: Optional[ChannelRegistry] = None,
                 code_loader=None,
                 code_details: Optional[dict] = None):
        self.factory = factory
        self.registry = registry
        self.code_loader = code_loader
        self.code_details = code_details

    def create_detached(self, document_id: str,
                        code_details: Optional[dict] = None) -> Container:
        service = self.factory.create_document_service(document_id)
        return Container.create_detached(
            document_id, service, self.registry, self.code_loader,
            code_details or self.code_details)

    def resolve(self, document_id: str,
                client_details: Optional[dict] = None) -> Container:
        service = self.factory.create_document_service(document_id)
        return Container.load(document_id, service, self.registry,
                              self.code_loader,
                              client_details=client_details)
