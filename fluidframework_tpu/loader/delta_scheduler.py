"""DeltaScheduler: time-sliced inbound op processing.

Capability parity with reference container-runtime/src/deltaScheduler.ts:25:
when a long catch-up drain is processing many sequenced ops back-to-back,
processing is interrupted every `quantum_ms` of wall time so the host
regains control (the reference pauses the inbound DeltaQueue and resumes on
a timer; here the DeltaManager releases the op lock and calls `yield_fn`,
letting application threads read DDS state between slices).

Counters (`batches`, `interruptions`, `ops_processed`) surface scheduling
behavior to telemetry, mirroring the reference's deltaScheduler telemetry
event (time-to-process over 2s gets logged there).
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class DeltaScheduler:
    DEFAULT_QUANTUM_MS = 20.0

    def __init__(self, quantum_ms: float = DEFAULT_QUANTUM_MS,
                 yield_fn: Optional[Callable[[], None]] = None):
        self.quantum_s = quantum_ms / 1000.0
        self.yield_fn = yield_fn or (lambda: time.sleep(0))
        self.batches = 0        # contiguous processing slices started
        self.interruptions = 0  # times processing yielded mid-drain
        self.ops_processed = 0
        self._slice_start: Optional[float] = None

    def op_started(self) -> None:
        if self._slice_start is None:
            self._slice_start = time.perf_counter()
            self.batches += 1

    def op_processed(self) -> None:
        self.ops_processed += 1

    def should_yield(self) -> bool:
        return (self._slice_start is not None
                and time.perf_counter() - self._slice_start > self.quantum_s)

    def on_yield(self) -> None:
        """Called by the DeltaManager with the op lock RELEASED."""
        self.interruptions += 1
        self._slice_start = None
        self.yield_fn()

    def drain_done(self) -> None:
        self._slice_start = None
