"""Code loader: resolve code details -> runtime-factory module.

Capability parity with reference packages/loader/web-code-loader (425 LoC,
`WebCodeLoader.load(IFluidCodeDetails) -> IFluidModule`) and the quorum
"code" proposal flow (container.ts code upgrade path; capability
negotiation, SURVEY.md §5 config): a container's *code details* — package
name + version range — select which registered runtime factory drives the
container. The reference fetches bundles from npm/CDN; here modules are
registered in-process (the TPU framework ships as one package), but the
resolution contract — semver-range matching over a registry, highest
matching version wins — is the same.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.semver import parse_version, satisfies  # noqa: F401 (re-export)


class FluidModule:
    """IFluidModule: the loaded bundle's entry point. `fluid_export` is the
    runtime factory (reference fluidExport convention)."""

    def __init__(self, fluid_export: Any, package: str, version: str):
        self.fluid_export = fluid_export
        self.package = package
        self.version = version


class CodeLoader:
    """ICodeLoader: registry of (package, version) -> runtime factory."""

    def __init__(self):
        self._registry: Dict[str, List[Tuple[str, Any]]] = {}

    def register(self, package: str, version: str, runtime_factory: Any
                 ) -> None:
        entries = self._registry.setdefault(package, [])
        # Re-registering a version replaces it (registry resolvers may
        # install the same resolved bundle for several containers).
        entries[:] = [(v, f) for v, f in entries if v != version]
        entries.append((version, runtime_factory))

    def load(self, details: Dict[str, Any]) -> FluidModule:
        """Resolve code details {"package": name, "version": range} to the
        highest registered version satisfying the range."""
        package = details["package"]
        spec = details.get("version", "*")
        candidates = [
            (parse_version(version), version, factory)
            for version, factory in self._registry.get(package, [])
            if satisfies(version, spec)]
        if not candidates:
            raise KeyError(
                f"no registered module satisfies {package}@{spec}")
        _, version, factory = max(candidates, key=lambda c: c[0])
        return FluidModule(factory, package, version)
