"""DeltaManager: the op pump between the wire and the container.

Capability parity with reference container-loader/src/deltaManager.ts:108 —
inbound queue with strict ordering, gap detection + catch-up fetch from
delta storage (:1380 fetchMissingDeltas), outbound submission with
clientSequenceNumber stamping, nack handling, and reconnect (new delta
connection, refetch, hand the container a fresh client id to resubmit on).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..core.events import TypedEventEmitter
from ..protocol.messages import (DocumentMessage, MessageType,
                                 SequencedDocumentMessage)
from ..telemetry import ChildLogger, OpRoundTripTelemetry, TelemetryLogger
from .delta_scheduler import DeltaScheduler
from .drivers.base import IDocumentService


class DeltaManager(TypedEventEmitter):
    """Events: "op" (each sequenced message, in order), "connect"
    (client_id), "disconnect", "nack", "signal" (SignalMessage — transient,
    NOT sequenced: no gap detection, no catch-up, no seq bookkeeping)."""

    def __init__(self, service: IDocumentService,
                 client_details: Optional[dict] = None,
                 logger: Optional[TelemetryLogger] = None,
                 scheduler: Optional[DeltaScheduler] = None):
        super().__init__()
        self.service = service
        self.client_details = client_details or {}
        self.scheduler = scheduler or DeltaScheduler()
        self.delta_storage = service.connect_to_delta_storage()
        self.connection = None
        self.client_id: Optional[str] = None
        self.last_sequence_number = 0
        self.client_sequence_number = 0
        self.minimum_sequence_number = 0
        self.logger = ChildLogger.create(logger, "DeltaManager")
        self._op_perf = OpRoundTripTelemetry(lambda: self.client_id,
                                             self.logger)
        self._handler: Optional[Callable[[SequencedDocumentMessage], None]] = None
        # Optional batch handler for the catch-up tail (device bulk path,
        # mergetree/catchup.py): receives the WHOLE contiguous fetched tail
        # at once when it is at least bulk_catchup_threshold long.
        self._bulk_handler: Optional[
            Callable[[List[SequencedDocumentMessage]], None]] = None
        self.bulk_catchup_threshold = 64
        # Optional artifact catch-up hook (docs/read_path.md): called at
        # the top of every catch-up with our position; when it adopts a
        # server catch-up artifact it advances last_sequence_number
        # itself (under self.lock) and returns the adopted seq — the
        # fetch loop below then covers only the residue past it.
        self._catchup_fetch: Optional[Callable[[int], Optional[int]]] = None
        self._inbound: List[SequencedDocumentMessage] = []
        self._processing = False
        # Inside an open inbound batch ({"batch": true} seen, closing
        # marker not yet): scheduler yields are held so the batch applies
        # atomically within one slice (reference DeltaScheduler batch
        # handling).
        self._in_batch = False
        # Noop heartbeat (reference deltaManager updateSequenceNumber): a
        # connected writer that only READS never tells the server its
        # refSeq advanced, pinning the MSN at its last submission. Send a
        # NO_OP carrying the fresh refSeq after noop_threshold remote ops
        # OR noop_idle_s of outbound silence (checked at delivery time) —
        # the time bound keeps live-but-idle writers well inside the
        # server's eviction window at any remote op rate. 0 disables each.
        self.noop_threshold = 25
        self.noop_idle_s = 2.25
        self._ops_since_submit = 0
        self._last_submit_time = time.monotonic()
        self._catching_up = False
        # The "event loop" of this container. In-process drivers deliver ops
        # synchronously on the caller's thread; network drivers deliver on a
        # websocket reader thread. Inbound processing and outbound submission
        # both serialize on this lock, and application code doing multi-
        # threaded DDS mutation takes it too (Container.op_lock).
        self.lock = threading.RLock()

    # -- lifecycle ---------------------------------------------------------
    def attach_op_handler(self, sequence_number: int,
                          handler: Callable[[SequencedDocumentMessage], None]
                          ) -> None:
        """Start pumping at sequence_number (the loaded summary's seq)."""
        self.last_sequence_number = sequence_number
        self._handler = handler

    def attach_bulk_handler(self, bulk_handler: Callable[
            [List[SequencedDocumentMessage]], None]) -> None:
        self._bulk_handler = bulk_handler

    def attach_catchup_fetch(self, fn: Callable[[int], Optional[int]]
                             ) -> None:
        self._catchup_fetch = fn

    def connect(self) -> str:
        self.connection = self.service.connect_to_delta_stream(
            self.client_details)
        self.client_id = self.connection.client_id
        self.client_sequence_number = 0
        # A batch left open by a mid-batch disconnect closes via the
        # refetched tail (batch members are durable contiguously), but the
        # flag must not leak across connections — and the bulk catch-up
        # path bypasses per-op metadata tracking entirely.
        self._in_batch = False
        self._ops_since_submit = 0
        self._last_submit_time = time.monotonic()
        self.connection.on("op", self._enqueue)
        self.connection.on("nack", lambda nack: self.emit("nack", nack))
        self.connection.on("signal", self._on_signal)
        self.connection.on("disconnect", lambda: self.emit("disconnect"))
        # Identity must be known to listeners BEFORE the op pump runs: the
        # catch-up tail contains our own join op, and the container runtime
        # goes "connected" by recognizing its client id in it.
        self.emit("connect", self.client_id)
        self.catch_up()
        return self.client_id

    def disconnect(self) -> None:
        if self.connection is not None:
            conn, self.connection = self.connection, None
            conn.close()

    def reconnect(self) -> str:
        """Drop the connection and establish a new identity; the container
        resubmits pending ops against it (deltaManager.ts:1119)."""
        self.disconnect()
        return self.connect()

    # -- outbound ----------------------------------------------------------
    def submit(self, mtype: str, contents, data: Optional[str] = None,
               before_send: Optional[Callable[[int], None]] = None) -> int:
        """Stamp and send one op. `before_send(csn)` runs after the
        clientSequenceNumber is assigned but before the wire push — callers
        record pending state there, because over an in-process service the
        sequenced ack can arrive synchronously inside the send."""
        with self.lock:
            if self.connection is None:
                raise ConnectionError("not connected")
            self.client_sequence_number += 1
            csn = self.client_sequence_number
            msg = DocumentMessage(
                client_sequence_number=csn,
                reference_sequence_number=self.last_sequence_number,
                type=mtype, contents=contents, data=data)
            if before_send is not None:
                before_send(csn)
            self._op_perf.on_submit(csn)
            self._ops_since_submit = 0
            self._last_submit_time = time.monotonic()
            self.connection.submit([msg])
            return csn

    def submit_batch(self, items, before_send=None) -> List[int]:
        """Send several ops as ONE wire submission (reference DeltaManager
        flush, deltaManager.ts:656-664): the whole list rides one boxcar,
        so the sequencer tickets it atomically — contiguous sequence
        numbers, no foreign op interleaved. Batch boundaries are marked in
        metadata ({"batch": true} on the first, {"batch": false} on the
        last) so receivers hold scheduler yields until the batch closes.
        `before_send(csn, contents)` runs per op before the wire push."""
        with self.lock:
            if self.connection is None:
                raise ConnectionError("not connected")
            msgs: List[DocumentMessage] = []
            csns: List[int] = []
            n = len(items)
            for i, (mtype, contents) in enumerate(items):
                self.client_sequence_number += 1
                csn = self.client_sequence_number
                metadata = None
                if n > 1:
                    if i == 0:
                        metadata = {"batch": True}
                    elif i == n - 1:
                        metadata = {"batch": False}
                msg = DocumentMessage(
                    client_sequence_number=csn,
                    reference_sequence_number=self.last_sequence_number,
                    type=mtype, contents=contents, metadata=metadata)
                if before_send is not None:
                    before_send(csn, contents)
                self._op_perf.on_submit(csn)
                msgs.append(msg)
                csns.append(csn)
            self._ops_since_submit = 0
            self._last_submit_time = time.monotonic()
            self.connection.submit(msgs)
            return csns

    def _on_signal(self, sig) -> None:
        # Same serialization contract as inbound ops: handlers run under
        # the container lock, so a signal handler reading DDS state never
        # races an application thread mutating it (op_lock docstring).
        with self.lock:
            self.emit("signal", sig)

    def submit_signal(self, content) -> None:
        """Send a transient signal (no clientSequenceNumber, no refSeq —
        signals live outside the sequenced stream entirely; reference
        deltaManager submitSignal passthrough)."""
        with self.lock:
            if self.connection is None:
                raise ConnectionError("not connected")
            self.connection.submit_signal(content)

    # -- inbound -----------------------------------------------------------
    def _enqueue(self, message: SequencedDocumentMessage) -> None:
        with self.lock:
            self._inbound.append(message)
        self._process_inbound()

    def _process_inbound(self) -> None:
        """Drain the inbound queue in sequence order. Deliveries happen
        under self.lock; gap-fill fetches (network I/O over remote drivers)
        happen with the lock RELEASED so application threads aren't stalled
        behind a slow/timed-out catch-up request."""
        while True:
            with self.lock:
                if self._processing:
                    return  # re-entrant deliveries drain in the outer loop
                self._processing = True
            gap: Optional[tuple] = None
            yielding = False
            try:
                with self.lock:
                    while self._inbound:
                        self._inbound.sort(key=lambda m: m.sequence_number)
                        msg = self._inbound[0]
                        if msg.sequence_number <= self.last_sequence_number:
                            self._inbound.pop(0)  # duplicate
                            continue
                        if msg.sequence_number > self.last_sequence_number + 1:
                            gap = (self.last_sequence_number,
                                   msg.sequence_number - 1)
                            # The fetch is network I/O, not processing time:
                            # close the slice so it isn't billed against the
                            # quantum (a spurious yield per gap otherwise).
                            self.scheduler.drain_done()
                            break
                        self._inbound.pop(0)
                        self.scheduler.op_started()
                        self._deliver(msg)
                        self.scheduler.op_processed()
                        meta = msg.metadata
                        if isinstance(meta, dict) and "batch" in meta:
                            self._in_batch = bool(meta["batch"])
                        if self.scheduler.should_yield() \
                                and not self._in_batch:
                            yielding = True
                            break
                    else:
                        self.scheduler.drain_done()
                if yielding:
                    self.scheduler.on_yield()  # lock released
                if gap is not None:
                    fetched = self.delta_storage.get(*gap)  # lock released
                    with self.lock:
                        self._inbound = fetched + self._inbound
            finally:
                self._processing = False
            with self.lock:
                # Another thread may have enqueued while we were fetching /
                # finishing the drain (its _process_inbound no-oped on the
                # _processing flag). Go around again only if the queue now
                # has something deliverable; an unfillable gap waits for the
                # next arrival instead of spinning.
                if not self._inbound:
                    return
                head = min(m.sequence_number for m in self._inbound)
                if head > self.last_sequence_number + 1:
                    return

    def _deliver(self, msg: SequencedDocumentMessage) -> None:
        self.last_sequence_number = msg.sequence_number
        self.minimum_sequence_number = msg.minimum_sequence_number
        self._op_perf.on_sequenced(msg)
        # Count remote non-noop activity only: counting noops would make
        # two idle clients answer each other's heartbeats forever.
        if msg.client_id is not None and msg.client_id != self.client_id \
                and msg.type != MessageType.NO_OP:
            self._ops_since_submit += 1
        if self._handler is not None:
            self._handler(msg)
        self.emit("op", msg)
        self._maybe_send_noop()

    def _maybe_send_noop(self) -> None:
        if self._ops_since_submit == 0:
            return  # nothing remote since our last submission
        count_due = (self.noop_threshold
                     and self._ops_since_submit >= self.noop_threshold)
        idle_due = (self.noop_idle_s and
                    time.monotonic() - self._last_submit_time
                    >= self.noop_idle_s)
        if not (count_due or idle_due):
            return
        if self._inbound or self._catching_up:
            # Mid-catch-up/drain: our refSeq is still behind the head and
            # deli would nack it (refSeq < MSN). Defer; the counter keeps
            # its value, so the heartbeat fires at the head.
            return
        if self.connection is None or \
                self.client_details.get("mode") == "read":
            self._ops_since_submit = 0  # readers cannot submit
            return
        try:
            self.submit(MessageType.NO_OP, None)
        except ConnectionError:
            # A concurrent disconnect raced the check above (close() nulls
            # the connection without the lock): a heartbeat is always safe
            # to drop, and it must never crash the delivery thread.
            pass

    def catch_up(self) -> None:
        """Fetch + process everything durable past our position
        (deltaManager.ts:1401). A long contiguous tail is handed to the
        bulk handler in one call — the device catch-up path — instead of
        per-op enqueueing; anything irregular falls back per-message."""
        self._catching_up = True
        try:
            self._catch_up()
        finally:
            self._catching_up = False
            self._maybe_send_noop()  # deferred heartbeat fires at the head

    def _catch_up(self) -> None:
        tail: List[SequencedDocumentMessage] = []
        tried_artifact = False
        while True:
            from_seq = (tail[-1].sequence_number if tail
                        else self.last_sequence_number)
            fetched = self.delta_storage.get(from_seq)
            if not fetched:
                break
            tail.extend(fetched)
            if self._catchup_fetch is not None and not tried_artifact \
                    and len(tail) >= self.bulk_catchup_threshold:
                # The read-tier fast path (docs/read_path.md), engaged
                # only once the tail is provably long — short gaps never
                # pay an artifact round trip. The hook owns its locking
                # and preconditions; on adoption it advances
                # last_sequence_number itself and returns the adopted
                # seq, and everything the artifact covers drops from the
                # fetched tail (the residue keeps replaying below).
                tried_artifact = True
                adopted = self._catchup_fetch(self.last_sequence_number)
                if adopted:
                    tail = [m for m in tail
                            if m.sequence_number > adopted]
        if not tail:
            return
        if (self._bulk_handler is not None
                and len(tail) >= self.bulk_catchup_threshold):
            with self.lock:
                # Revalidate under the lock: the connection's reader thread
                # may have delivered (and processed) a prefix of this tail
                # concurrently — drop what is already applied and require
                # gapless continuation from our position.
                live = [m for m in tail
                        if m.sequence_number > self.last_sequence_number]
                contiguous = all(
                    m.sequence_number == self.last_sequence_number + 1 + i
                    for i, m in enumerate(live))
                if contiguous and \
                        len(live) >= self.bulk_catchup_threshold:
                    self._bulk_handler(live)
                    self.last_sequence_number = live[-1].sequence_number
                    self.minimum_sequence_number = \
                        live[-1].minimum_sequence_number
                    # The bulk path applied any batch markers wholesale.
                    self._in_batch = False
                    return
        for msg in tail:
            self._enqueue(msg)
