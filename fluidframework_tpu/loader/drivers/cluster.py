"""Cluster driver: the local driver's multi-node sibling.

Connects a Loader/Container to a `server.nodes.Cluster` through a chosen
entry node (any node reaches any document — non-owners proxy, reference
proxyOrderer.ts). `set_node()` repoints the factory after a node failure;
the next (re)connect goes through the new node, which takes the document
reservation over and resumes from the shared checkpoints.
"""

from __future__ import annotations

from typing import List, Optional

from ...protocol.summary import SummaryTree
from ...server.nodes import Cluster, OrdererNode
from ...telemetry.counters import record_swallow
from .base import (
    IDocumentDeltaConnection,
    IDocumentDeltaStorageService,
    IDocumentService,
    IDocumentServiceFactory,
    IDocumentStorageService,
)
from .local import _row_to_message


class ClusterDocumentStorageService(IDocumentStorageService):
    def __init__(self, cluster: Cluster, document_id: str,
                 historian_tier=None):
        self.cluster = cluster
        self.document_id = document_id
        self.historian_tier = historian_tier
        self.store = cluster.historian.store(cluster.tenant_id, document_id)

    def get_summary(self, version: Optional[str] = None):
        tier = self.historian_tier
        if tier is not None:
            # Reads ride the cache tier; a dead/poisoned tier degrades to
            # the direct store below (same contract as the network
            # driver's historian fallback).
            try:
                from ...protocol.summary import summary_tree_from_dict
                data = tier.read_summary_dict(
                    self.cluster.tenant_id, self.document_id,
                    commit_sha=version)
                return (summary_tree_from_dict(data)
                        if data is not None else None)
            except Exception:  # noqa: BLE001 — tier failure, not data
                # Degrade to the direct store read below; counted so a
                # dead historian tier is visible as a rate, not silence.
                record_swallow("driver.historian_tier")
        return self.cluster.historian.read_summary(
            self.cluster.tenant_id, self.document_id, commit_sha=version)

    def upload_summary(self, summary: SummaryTree,
                       parent: Optional[str] = None,
                       initial: bool = False) -> str:
        return self.store.write_summary(summary, base_commit=parent,
                                        advance_ref=initial)

    def get_versions(self, count: int = 1) -> List[str]:
        return [c.sha for c in self.store.list_commits(limit=count)]


class ClusterDeltaStorageService(IDocumentDeltaStorageService):
    def __init__(self, factory: "ClusterDocumentServiceFactory",
                 document_id: str):
        self.factory = factory
        self.document_id = document_id

    def get(self, from_seq: int, to_seq: Optional[int] = None):
        rows = self.factory.node.get_deltas(self.document_id, from_seq,
                                            to_seq)
        return [_row_to_message(r) for r in rows]


class ClusterDocumentDeltaConnection(IDocumentDeltaConnection):
    def __init__(self, node: OrdererNode, document_id: str,
                 client_details: Optional[dict]):
        self._conn = node.connect(document_id, client_details)
        self.client_id = self._conn.client_id

    def submit(self, messages) -> None:
        self._conn.submit(messages)

    def submit_signal(self, content) -> None:
        self._conn.submit_signal(content)

    def on(self, event, fn) -> None:
        self._conn.on(event, fn)

    def off(self, event, fn) -> None:
        self._conn.off(event, fn)

    def close(self) -> None:
        self._conn.disconnect()


class ClusterDocumentService(IDocumentService):
    def __init__(self, factory: "ClusterDocumentServiceFactory",
                 document_id: str):
        self.factory = factory
        self.document_id = document_id

    def connect_to_storage(self):
        return ClusterDocumentStorageService(
            self.factory.cluster, self.document_id,
            historian_tier=self.factory.historian_tier)

    def connect_to_delta_storage(self):
        return ClusterDeltaStorageService(self.factory, self.document_id)

    def connect_to_delta_stream(self, client_details=None):
        # Resolved at call time so reconnects pick up a node switched via
        # set_node() after the previous entry node died.
        return ClusterDocumentDeltaConnection(self.factory.node,
                                              self.document_id,
                                              client_details)


class ClusterDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, cluster: Cluster, node: OrdererNode,
                 historian_tier=None):
        """historian_tier: an embedded server/historian.py HistorianTier
        over the cluster's shared store — summary reads then serve from
        its cache on every node, surviving node failovers (the cache is
        keyed by content, not by node)."""
        self.cluster = cluster
        self.node = node
        self.historian_tier = historian_tier

    def set_node(self, node: OrdererNode) -> None:
        """Repoint at a different entry node (failover)."""
        self.node = node

    def create_document_service(self, document_id: str) -> IDocumentService:
        return ClusterDocumentService(self, document_id)
