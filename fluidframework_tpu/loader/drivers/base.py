"""Driver SPI (reference packages/loader/driver-definitions/src/storage.ts:
30-259): the service abstraction the loader consumes. A driver provides
storage (summaries/blobs), delta storage (catch-up reads), and a delta
connection (live op stream) for one document.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ...protocol.messages import DocumentMessage, SequencedDocumentMessage
from ...protocol.summary import SummaryTree


class IDocumentStorageService:
    def get_summary(self, version: Optional[str] = None
                    ) -> Optional[SummaryTree]:
        raise NotImplementedError

    def upload_summary(self, summary: SummaryTree,
                       parent: Optional[str] = None,
                       initial: bool = False) -> str:
        """Returns the storage handle (commit sha) for a summarize op.
        initial=True marks the attach summary, which becomes the load
        target directly; other uploads await a scribe summaryAck."""
        raise NotImplementedError

    def get_versions(self, count: int = 1) -> List[str]:
        raise NotImplementedError

    def get_catchup(self):
        """`summary + delta` in one round trip (docs/read_path.md):
        returns (summary_tree, catchup_artifact_or_None). The artifact is
        the serving tier's per-doc incremental catch-up state
        (server/readpath.py); drivers without a read tier return the
        summary with None and the loader tail-replays — the always-
        correct fallback this default encodes."""
        return self.get_summary(), None

    def get_catchup_artifact(self):
        """Artifact-only fetch (the reconnect path: the client already
        holds a summary-derived state and only wants the delta)."""
        return None


class IDocumentDeltaStorageService:
    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        raise NotImplementedError


class IDocumentDeltaConnection:
    """Live connection: .client_id, .submit(), .submit_signal(), events via
    .on('op'|'nack'|'signal'|'disconnect', fn), .close()."""

    client_id: str

    def submit(self, messages: List[DocumentMessage]) -> None:
        raise NotImplementedError

    def submit_signal(self, content: Any) -> None:
        """Transient broadcast to the document's room; bypasses sequencing
        (reference IDocumentDeltaConnection.submitSignal). Read-only
        connections (replay/file) reject it."""
        raise NotImplementedError

    def on(self, event: str, fn: Callable) -> None:
        raise NotImplementedError

    def off(self, event: str, fn: Callable) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class IDocumentService:
    def connect_to_storage(self) -> IDocumentStorageService:
        raise NotImplementedError

    def connect_to_delta_storage(self) -> IDocumentDeltaStorageService:
        raise NotImplementedError

    def connect_to_delta_stream(self, client_details: Optional[dict] = None
                                ) -> IDocumentDeltaConnection:
        raise NotImplementedError


class IDocumentServiceFactory:
    def create_document_service(self, document_id: str) -> IDocumentService:
        raise NotImplementedError
