"""Network driver: REST storage/delta catch-up + websocket delta stream.

Capability parity with reference packages/drivers/routerlicious-driver
(`src/documentService.ts`, `documentDeltaConnection.ts`,
`deltaStorageService.ts`, `documentStorageService.ts`) and driver-base
(`src/documentDeltaConnection.ts`): the production driver that talks to an
Alfred front door (server/alfred.py) over real sockets. Token minting
follows the reference's ITokenProvider pattern — the host supplies a
callable returning a JWT for (tenantId, documentId).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, List, Optional

from ...core.events import TypedEventEmitter
from ...protocol.messages import (DocumentMessage,
                                  SequencedDocumentMessage, SignalMessage)
from ...protocol.summary import (
    SummaryTree,
    summary_tree_from_dict,
    summary_tree_to_dict,
)
from ...server import websocket
from ...telemetry import tracing
from ...server.wire import (
    delta_rows_to_messages,
    document_message_to_dict,
    nack_from_dict,
    sequenced_message_from_dict,
)
from .base import (
    IDocumentDeltaConnection,
    IDocumentDeltaStorageService,
    IDocumentService,
    IDocumentServiceFactory,
    IDocumentStorageService,
)

TokenProvider = Callable[[str, str], str]  # (tenant_id, document_id) -> jwt


def _q(segment: str) -> str:
    """Percent-encode a caller-supplied id for use as one URL path/query
    segment (ids may contain spaces, '#', '%', ...)."""
    return urllib.parse.quote(str(segment), safe="")


class RestWrapper:
    """Thin authenticated JSON REST client (reference services-client
    RestWrapper)."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def request(self, method: str, path: str,
                body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base_url + path, data=data,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            raise RestError(exc.code, detail) from exc

    def get(self, path: str) -> dict:
        return self.request("GET", path)

    def post(self, path: str, body: Optional[dict] = None) -> dict:
        return self.request("POST", path, body or {})


class RestError(Exception):
    def __init__(self, status: int, detail: str):
        super().__init__(f"HTTP {status}: {detail}")
        self.status = status
        self.detail = detail


RestFactory = Callable[[], RestWrapper]


def _never_sent(exc: OSError) -> bool:
    """True when the transport error proves the request never reached the
    server (safe to replay a non-idempotent call elsewhere)."""
    if isinstance(exc, ConnectionRefusedError):
        return True
    reason = getattr(exc, "reason", None)  # urllib wraps in URLError
    return isinstance(reason, ConnectionRefusedError)


class NetworkDocumentStorageService(IDocumentStorageService):
    """Summary upload/download over the historian REST routes. Takes a
    RestWrapper *factory* so every request gets a freshly minted token —
    these services are long-lived and tokens expire.

    historian_factory: a second RestFactory pointed at a standalone
    summary-cache tier (server/historian.py). When set, storage traffic
    rides the tier — reads serve from its object cache, uploads
    write-through it — and a dead tier degrades to the direct endpoint
    (sticky per service instance, so one mid-load kill costs one timeout,
    not one per blob)."""

    def __init__(self, rest_factory: RestFactory, tenant_id: str,
                 document_id: str,
                 historian_factory: Optional[RestFactory] = None):
        self._rest = rest_factory
        self._historian = historian_factory
        self._historian_down = False
        self.tenant_id = tenant_id
        self.document_id = document_id
        self._repo = f"/repos/{_q(tenant_id)}/{_q(document_id)}"

    def _call(self, fn, idempotent: bool = True):
        """fn(rest) against the cache tier first; transport failure or a
        503 (tier lost ITS upstream) falls back to the direct endpoint —
        the historian-killed-mid-load degradation path.

        idempotent=False (summary uploads): an AMBIGUOUS transport error
        (timeout / reset mid-flight) must NOT replay against the direct
        endpoint — the tier may already have committed the write (a
        replayed initial upload would 409 a document that was in fact
        created; a replayed proposal would orphan a duplicate commit).
        Only a provably-unsent request (connection refused) falls back."""
        if self._historian is not None and not self._historian_down:
            try:
                return fn(self._historian())
            except RestError as exc:
                if exc.status != 503:
                    raise
                self._historian_down = True
            except OSError as exc:
                self._historian_down = True
                if not idempotent and not _never_sent(exc):
                    raise
        return fn(self._rest())

    def get_summary(self, version: Optional[str] = None
                    ) -> Optional[SummaryTree]:
        path = self._repo + "/summaries/latest"
        if version:
            path += f"?sha={_q(version)}"
        try:
            data = self._call(lambda rest: rest.get(path))
        except RestError as exc:
            if exc.status == 404:
                return None
            raise
        return summary_tree_from_dict(data["summary"])

    def upload_summary(self, summary: SummaryTree,
                       parent: Optional[str] = None,
                       initial: bool = False) -> str:
        body = {
            "summary": summary_tree_to_dict(summary),
            "parent": parent,
            "initial": initial,
        }
        return self._call(
            lambda rest: rest.post(self._repo + "/summaries", body),
            idempotent=False)["sha"]

    def get_versions(self, count: int = 1) -> List[str]:
        return self._call(lambda rest: rest.get(
            self._repo + f"/versions?count={count}"))["versions"]

    def get_catchup(self):
        """`summary + delta` in ONE historian round trip (the read tier's
        `/catchup` route, docs/read_path.md). A tier without the route
        (404), a dead tier, or a tier with no artifact degrades to the
        plain summary read — the loader then tail-replays."""
        try:
            data = self._call(lambda rest: rest.get(
                self._repo + "/catchup"))
        except RestError as exc:
            if exc.status in (404, 501):
                return self.get_summary(), None
            raise
        except OSError:
            return self.get_summary(), None
        summary = data.get("summary")
        artifact = data.get("catchup")
        if summary is None:
            return self.get_summary(), artifact
        return summary_tree_from_dict(summary), artifact

    def get_catchup_artifact(self):
        try:
            data = self._call(lambda rest: rest.get(
                self._repo + "/catchup?artifactOnly=1"))
        except (RestError, OSError):
            return None
        return data.get("catchup")


class NetworkDeltaStorageService(IDocumentDeltaStorageService):
    """Catch-up reads over the alfred delta REST route."""

    def __init__(self, rest_factory: RestFactory, tenant_id: str,
                 document_id: str):
        self._rest = rest_factory
        self.path = f"/deltas/{_q(tenant_id)}/{_q(document_id)}"

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        path = self.path + f"?from={from_seq}"
        if to_seq is not None:
            path += f"&to={to_seq}"
        return delta_rows_to_messages(self._rest().get(path)["deltas"])


class NetworkDocumentDeltaConnection(TypedEventEmitter,
                                     IDocumentDeltaConnection):
    """The live op stream over a websocket. A reader thread dispatches
    server frames to "op"/"nack"/"signal"/"disconnect" listeners — same
    event surface as the local driver so DeltaManager is agnostic."""

    def __init__(self, host: str, port: int, tenant_id: str,
                 document_id: str, token: Optional[str],
                 client_details: Optional[dict]):
        TypedEventEmitter.__init__(self)
        self._ws = websocket.connect(host, port, "/socket")
        self._ws.send_text(json.dumps({
            "type": "connect_document",
            "tenantId": tenant_id,
            "documentId": document_id,
            "token": token,
            "client": client_details or {},
        }))
        # The server registers broadcast listeners before sending
        # "connected", so a busy document can push op frames ahead of the
        # handshake reply. Skip them — they are already durable (the server
        # persists before broadcasting) and the post-connect catch-up fetch
        # replays them in order.
        while True:
            hello = json.loads(self._ws.recv())
            htype = hello.get("type")
            if htype == "connected":
                break
            if htype in ("op", "nack", "signal"):
                # Ops replay via catch-up; a pre-handshake signal is
                # droppable by definition (transient, no ordering contract).
                continue
            self._ws.close()
            raise ConnectionError(
                f"connect_document rejected: {hello.get('error', hello)}")
        self.client_id = hello["clientId"]
        self.checkpoint_sequence_number = hello.get("sequenceNumber", 0)
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"ws-{self.client_id}",
                                        daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                frame = json.loads(self._ws.recv())
                ftype = frame.get("type")
                if ftype == "op":
                    self.emit("op",
                              sequenced_message_from_dict(frame["message"]))
                elif ftype == "nack":
                    self.emit("nack", nack_from_dict(frame["nack"]))
                elif ftype == "signal":
                    self.emit("signal", SignalMessage(
                        client_id=frame.get("clientId"),
                        content=frame.get("content")))
        except (websocket.WebSocketClosed, OSError,
                json.JSONDecodeError, ValueError, RestError):
            # RestError: an op handler's catch-up fetch failed (e.g. expired
            # token); treat like a dropped connection so the container's
            # disconnect/reconnect path takes over instead of a dead thread.
            pass
        finally:
            if not self._closed:
                self._closed = True
                self.emit("disconnect")

    def submit(self, messages: List[DocumentMessage]) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        # Trace context onto the wire: metadata serializes inside
        # document_message_to_dict, so the context survives the socket
        # hop into alfred's ingest verbatim.
        ctx = tracing.ensure_op_context()
        if ctx is not None:
            for msg in messages:
                tracing.stamp_message(msg, ctx)
        with tracing.span("driver.submit", parent=ctx, transport="ws",
                          count=len(messages)):
            self._ws.send_text(json.dumps({
                "type": "submitOp",
                "messages": [document_message_to_dict(m)
                             for m in messages],
            }))

    def submit_signal(self, content) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        try:
            self._ws.send_text(json.dumps(
                {"type": "submitSignal", "content": content}))
        except websocket.WebSocketClosed as exc:
            # The reader thread flips the websocket's flag before ours:
            # normalize to the ConnectionError the runtime's drop-don't-
            # raise contract catches.
            raise ConnectionError(str(exc)) from exc

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._ws.send_text(json.dumps({"type": "disconnect"}))
        except (websocket.WebSocketClosed, OSError):
            pass
        self._ws.close()
        # close() can be reached from the reader thread itself (e.g. a nack
        # handler triggering reconnect); a thread cannot join itself.
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=5)


class NetworkDocumentService(IDocumentService):
    def __init__(self, base_url: str, tenant_id: str, document_id: str,
                 token_provider: Optional[TokenProvider],
                 mux_pool=None, session_cache=None,
                 historian_url: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.tenant_id = tenant_id
        self.document_id = document_id
        self.token_provider = token_provider
        self.historian_url = (historian_url.rstrip("/")
                              if historian_url else None)
        # Set by a multiplexing factory: shared socket pool + join-session
        # discovery cache (loader/drivers/mux.py).
        self._mux_pool = mux_pool
        self._session_cache = session_cache
        without_scheme = self.base_url.split("://", 1)[-1]
        host, _, port = without_scheme.partition(":")
        self._host, self._port = host, int(port or 80)

    def _token(self) -> Optional[str]:
        if self.token_provider is None:
            return None
        return self.token_provider(self.tenant_id, self.document_id)

    def _rest(self) -> RestWrapper:
        return RestWrapper(self.base_url, self._token())

    def _historian_rest(self) -> RestWrapper:
        # Same bearer token: the tier forwards it upstream, so alfred's
        # riddler validation still gates every cached read.
        return RestWrapper(self.historian_url, self._token())

    def connect_to_storage(self) -> NetworkDocumentStorageService:
        return NetworkDocumentStorageService(
            self._rest, self.tenant_id, self.document_id,
            historian_factory=(self._historian_rest
                               if self.historian_url else None))

    def connect_to_delta_storage(self) -> NetworkDeltaStorageService:
        return NetworkDeltaStorageService(self._rest, self.tenant_id,
                                          self.document_id)

    def connect_to_delta_stream(self, client_details: Optional[dict] = None
                                ) -> IDocumentDeltaConnection:
        if self._mux_pool is None:
            return NetworkDocumentDeltaConnection(
                self._host, self._port, self.tenant_id, self.document_id,
                self._token(), client_details)
        # Multiplexed path: discover the socket endpoint (join-session),
        # then ride the pooled socket for that endpoint. A dead pooled
        # socket fails the first attempt; refresh the discovery and retry
        # once on a fresh socket.
        for attempt in (0, 1):
            discovery = self._session_cache.get(self.tenant_id,
                                                self.document_id)
            manager = self._mux_pool.manager(
                discovery["socketHost"], discovery["socketPort"],
                discovery.get("socketPath", "/socket-mux"))
            try:
                return manager.connect_document(
                    self.tenant_id, self.document_id, self._token(),
                    client_details)
            except ConnectionError:
                self._session_cache.invalidate(self.tenant_id,
                                               self.document_id)
                if attempt:
                    raise


class NetworkDocumentServiceFactory(IDocumentServiceFactory):
    """Driver entry point: points at an alfred URL + tenant, mints a
    document service per document.

    multiplex=True turns on the odsp-style connection management: the
    delta stream is discovered per document via the join-session REST
    call and documents on the same endpoint share ONE physical websocket
    (loader/drivers/mux.py).

    historian_url points storage traffic at a standalone summary-cache
    tier (server/historian.py); second-and-later container loads then
    serve summary blobs from its cache instead of GitStore, degrading to
    base_url if the tier is down."""

    def __init__(self, base_url: str, tenant_id: str,
                 token_provider: Optional[TokenProvider] = None,
                 multiplex: bool = False,
                 historian_url: Optional[str] = None):
        self.base_url = base_url
        self.tenant_id = tenant_id
        self.token_provider = token_provider
        self.historian_url = historian_url
        if multiplex:
            from .mux import JoinSessionCache, MuxConnectionPool
            self.mux_pool = MuxConnectionPool()
            self.session_cache = JoinSessionCache(self._fetch_session)
        else:
            self.mux_pool = None
            self.session_cache = None

    def _fetch_session(self, tenant_id: str, document_id: str) -> dict:
        token = (self.token_provider(tenant_id, document_id)
                 if self.token_provider else None)
        rest = RestWrapper(self.base_url, token)
        return rest.get(f"/api/v1/session/{_q(tenant_id)}/{_q(document_id)}")

    def set_historian_endpoint(self, historian_url: Optional[str]) -> None:
        """Repoint storage reads at a cache tier (or None to detach);
        affects services created afterwards."""
        self.historian_url = historian_url

    def create_document_service(self, document_id: str
                                ) -> NetworkDocumentService:
        return NetworkDocumentService(self.base_url, self.tenant_id,
                                      document_id, self.token_provider,
                                      mux_pool=self.mux_pool,
                                      session_cache=self.session_cache,
                                      historian_url=self.historian_url)

    def create_document(self, document_id: Optional[str] = None,
                        summary: Optional[SummaryTree] = None) -> str:
        """POST /documents (reference createDoc flow). Returns the doc id."""
        token = (self.token_provider(self.tenant_id, document_id or "*")
                 if self.token_provider else None)
        body: dict = {}
        if document_id:
            body["id"] = document_id
        if summary is not None:
            body["summary"] = summary_tree_to_dict(summary)
        rest = RestWrapper(self.base_url, token)
        return rest.post(f"/documents/{_q(self.tenant_id)}", body)["id"]
