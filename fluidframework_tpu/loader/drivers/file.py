"""File driver: snapshots + ops on the local filesystem.

Capability parity with reference packages/drivers/file-driver
(fileDocumentService.ts): a document is a directory holding summary.json
(the summary tree) and ops.json (the sequenced op log). Reading gives a
live-loadable document; writing captures a session for later replay
(fetch-tool writes this format; replay-tool reads it)."""

from __future__ import annotations

import json
import os
from typing import List, Optional

from ...protocol.messages import SequencedDocumentMessage
from ...protocol.summary import (
    SummaryBlob,
    SummaryObject,
    SummaryTree,
    blob_sha,
)
from .base import (
    IDocumentDeltaStorageService,
    IDocumentService,
    IDocumentServiceFactory,
    IDocumentStorageService,
)
from .replay import ReplayController, ReplayDeltaConnection


def summary_to_json(node: SummaryObject):
    if isinstance(node, SummaryBlob):
        return {"type": "blob", "content": node.content
                if isinstance(node.content, str)
                else node.content.decode("latin-1")}
    return {"type": "tree", "entries": {
        name: summary_to_json(child)
        for name, child in node.entries.items()}}


def summary_from_json(data) -> SummaryObject:
    if data["type"] == "blob":
        return SummaryBlob(data["content"])
    tree = SummaryTree()
    for name, child in data["entries"].items():
        tree.entries[name] = summary_from_json(child)
    return tree


def message_to_json(m: SequencedDocumentMessage) -> dict:
    return {
        "clientId": m.client_id,
        "sequenceNumber": m.sequence_number,
        "minimumSequenceNumber": m.minimum_sequence_number,
        "clientSequenceNumber": m.client_sequence_number,
        "referenceSequenceNumber": m.reference_sequence_number,
        "type": m.type,
        "contents": m.contents,
        "data": m.data,
        "timestamp": m.timestamp,
    }


def message_from_json(d: dict) -> SequencedDocumentMessage:
    return SequencedDocumentMessage(
        client_id=d.get("clientId"),
        sequence_number=d["sequenceNumber"],
        minimum_sequence_number=d.get("minimumSequenceNumber", 0),
        client_sequence_number=d.get("clientSequenceNumber", 0),
        reference_sequence_number=d.get("referenceSequenceNumber", 0),
        type=d["type"],
        contents=d.get("contents"),
        data=d.get("data"),
        timestamp=d.get("timestamp", 0.0),
    )


class FileDocumentCapture:
    """Read/write access to one on-disk document directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def summary_path(self) -> str:
        return os.path.join(self.directory, "summary.json")

    @property
    def ops_path(self) -> str:
        return os.path.join(self.directory, "ops.json")

    def write_summary(self, summary: SummaryTree) -> str:
        data = summary_to_json(summary)
        with open(self.summary_path, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        return blob_sha(json.dumps(data, sort_keys=True))

    def read_summary(self) -> Optional[SummaryTree]:
        if not os.path.exists(self.summary_path):
            return None
        with open(self.summary_path) as f:
            return summary_from_json(json.load(f))

    def write_ops(self, ops: List[SequencedDocumentMessage]) -> None:
        with open(self.ops_path, "w") as f:
            json.dump([message_to_json(m) for m in ops], f, indent=1)

    def append_ops(self, ops: List[SequencedDocumentMessage]) -> None:
        self.write_ops(self.read_ops() + list(ops))

    def read_ops(self) -> List[SequencedDocumentMessage]:
        if not os.path.exists(self.ops_path):
            return []
        with open(self.ops_path) as f:
            return [message_from_json(d) for d in json.load(f)]


class FileStorageService(IDocumentStorageService):
    def __init__(self, capture: FileDocumentCapture):
        self.capture = capture

    def get_summary(self, version: Optional[str] = None):
        return self.capture.read_summary()

    def upload_summary(self, summary, parent=None, initial=False) -> str:
        return self.capture.write_summary(summary)

    def get_versions(self, count: int = 1) -> List[str]:
        summary = self.capture.read_summary()
        if summary is None:
            return []
        return [blob_sha(json.dumps(summary_to_json(summary),
                                    sort_keys=True))]


class FileDeltaStorage(IDocumentDeltaStorageService):
    def __init__(self, capture: FileDocumentCapture):
        self.capture = capture

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        return [m for m in self.capture.read_ops()
                if m.sequence_number > from_seq
                and (to_seq is None or m.sequence_number <= to_seq)]


class FileDocumentService(IDocumentService):
    """Read path: load summary + replay the on-disk op tail (read-only
    connection, as the reference file driver is)."""

    def __init__(self, capture: FileDocumentCapture):
        self.capture = capture

    def connect_to_storage(self):
        return FileStorageService(self.capture)

    def connect_to_delta_storage(self):
        return FileDeltaStorage(self.capture)

    def connect_to_delta_stream(self, client_details=None):
        return ReplayDeltaConnection(self.capture.read_ops(),
                                     ReplayController())


class FileDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, root_directory: str):
        self.root = root_directory

    def create_document_service(self, document_id: str) -> IDocumentService:
        return FileDocumentService(
            FileDocumentCapture(os.path.join(self.root, document_id)))
