"""Multiplexed network driver: many documents over one physical websocket,
discovered via the join-session flow.

Capability parity with the reference odsp-driver's production connection
management (packages/drivers/odsp-driver/src, 6,713 LoC): (a) joinSession —
a REST call discovers the socket endpoint for a document before connecting,
with the discovery cached until its expiry; (b) socket references — one
physical socket per endpoint shared by every document connected through
it, refcounted, torn down when the last document disconnects or the socket
dies. The wire protocol is alfred's `/socket-mux` frame set (legacy frames
plus a client-chosen connection id `cid`).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...core.events import Deferred, TypedEventEmitter
from ...protocol.messages import DocumentMessage, SignalMessage
from ...server import websocket
from ...server.wire import (
    document_message_to_dict,
    nack_from_dict,
    sequenced_message_from_dict,
)
from .base import IDocumentDeltaConnection


class JoinSessionCache:
    """Caches session discoveries per (tenant, document) until expiry
    (odsp joinSession + its cached ISocketStorageDiscovery)."""

    def __init__(self, fetch: Callable[[str, str], dict]):
        self._fetch = fetch
        self._cache: Dict[Tuple[str, str], Tuple[float, dict]] = {}
        self._lock = threading.Lock()

    def get(self, tenant_id: str, document_id: str) -> dict:
        key = (tenant_id, document_id)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and hit[0] > now:
                return hit[1]
        discovery = self._fetch(tenant_id, document_id)
        expiry = now + discovery.get("sessionExpiryMs", 600_000) / 1000.0
        with self._lock:
            self._cache[key] = (expiry, discovery)
        return discovery

    def invalidate(self, tenant_id: str, document_id: str) -> None:
        with self._lock:
            self._cache.pop((tenant_id, document_id), None)


class MuxDeltaConnection(TypedEventEmitter, IDocumentDeltaConnection):
    """One document's delta connection riding a shared socket. Same event
    surface as every other driver connection; close() detaches only this
    document (the socket lives while other documents ride it)."""

    def __init__(self, manager: "MuxSocketManager", cid: int,
                 client_id: str, checkpoint_sequence_number: int):
        TypedEventEmitter.__init__(self)
        self._manager = manager
        self._cid = cid
        self.client_id = client_id
        self.checkpoint_sequence_number = checkpoint_sequence_number
        self._closed = False
        self._sock = None  # the physical socket this connection rides

    def submit(self, messages: List[DocumentMessage]) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        self._manager.send({
            "type": "submitOp", "cid": self._cid,
            "messages": [document_message_to_dict(m) for m in messages]})

    def submit_signal(self, content) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        self._manager.send({"type": "submitSignal", "cid": self._cid,
                            "content": content})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._manager.detach(self._cid)

    # called by the manager's reader thread
    def _dispatch(self, frame: dict) -> None:
        ftype = frame.get("type")
        if ftype == "op":
            self.emit("op", sequenced_message_from_dict(frame["message"]))
        elif ftype == "nack":
            self.emit("nack", nack_from_dict(frame["nack"]))
        elif ftype == "signal":
            self.emit("signal", SignalMessage(
                client_id=frame.get("clientId"),
                content=frame.get("content")))
        elif ftype == "error":
            # Server-side per-document error (alfred's cid isolation):
            # surface it to whoever listens; an unobserved error frame is
            # at least observable, not silently identical to a dropped op.
            self.emit("error", frame.get("error"))

    def _on_socket_dead(self) -> None:
        if not self._closed:
            self._closed = True
            self.emit("disconnect")


class MuxSocketManager:
    """One physical websocket to one `/socket-mux` endpoint, shared by all
    documents connected through it (the odsp socket-reference). Dead socket
    => every riding connection gets "disconnect"; the next connect_document
    dials a fresh socket."""

    def __init__(self, host: str, port: int, path: str = "/socket-mux"):
        self.host, self.port, self.path = host, port, path
        self._ws: Optional[websocket.WebSocketConnection] = None
        self._reader: Optional[threading.Thread] = None
        self._conns: Dict[int, MuxDeltaConnection] = {}
        self._handshakes: Dict[int, Deferred] = {}
        self._cids = itertools.count(1)
        self._lock = threading.RLock()

    @property
    def socket_alive(self) -> bool:
        return self._ws is not None and not self._ws.closed

    @property
    def document_count(self) -> int:
        return len(self._conns)

    def _ensure_socket(self) -> websocket.WebSocketConnection:
        with self._lock:
            if self.socket_alive:
                return self._ws
            self._ws = websocket.connect(self.host, self.port, self.path)
            self._reader = threading.Thread(
                target=self._read_loop, args=(self._ws,),
                name=f"ws-mux-{self.host}:{self.port}", daemon=True)
            self._reader.start()
            return self._ws

    def send(self, payload: dict) -> None:
        with self._lock:
            ws = self._ws
        if ws is None or ws.closed:
            raise ConnectionError("mux socket closed")
        try:
            ws.send_text(json.dumps(payload))
        except websocket.WebSocketClosed as exc:
            raise ConnectionError(str(exc)) from exc

    def connect_document(self, tenant_id: str, document_id: str,
                         token: Optional[str],
                         client_details: Optional[dict],
                         timeout: float = 30.0) -> MuxDeltaConnection:
        cid = next(self._cids)
        # Register the connection BEFORE the handshake resolves: the server
        # broadcasts room frames the instant the document is joined, so ops
        # for this cid can arrive ahead of (or interleaved with) the
        # "connected" reply on the reader thread — they must find a
        # dispatch target, and a socket death in that window must deliver
        # this connection its "disconnect".
        conn = MuxDeltaConnection(self, cid, client_id=None,
                                  checkpoint_sequence_number=0)
        deferred = Deferred()
        with self._lock:
            # Socket acquisition and registration are ONE atomic step: a
            # concurrent last-rider detach either sees this registration
            # (and keeps the socket) or finishes releasing the socket
            # first (and _ensure_socket dials a fresh one) — it can never
            # close the socket under a half-registered handshake.
            ws = self._ensure_socket()
            conn._sock = ws
            deferred.sock = ws  # scope dead-socket cleanup to this socket
            self._handshakes[cid] = deferred
            self._conns[cid] = conn
        try:
            self.send({"type": "connect_document", "cid": cid,
                       "tenantId": tenant_id, "documentId": document_id,
                       "token": token, "client": client_details or {}})
            hello = deferred.result(timeout)
            if hello.get("type") != "connected":
                raise ConnectionError(
                    f"connect_document rejected: "
                    f"{hello.get('error', hello)}")
        except BaseException:
            # Unregister the handshake BEFORE detaching so detach's
            # last-rider count sees the truth; detach then tells the
            # server to let go of the document (it may have joined — e.g.
            # handshake timeout raced the reply) AND releases the socket +
            # reader thread if this failed connect was the only rider.
            with self._lock:
                self._handshakes.pop(cid, None)
            self.detach(cid)
            raise
        finally:
            with self._lock:
                self._handshakes.pop(cid, None)
        conn.client_id = hello["clientId"]
        conn.checkpoint_sequence_number = hello.get("sequenceNumber", 0)
        return conn

    def detach(self, cid: int) -> None:
        # The last-rider DECISION commits under the lock by unpublishing
        # the socket (racing connect_documents then dial fresh instead of
        # adopting a socket mid-teardown), but the teardown I/O itself
        # runs outside it — a blocked send must not stall the reader
        # thread's per-frame lock acquisitions for sibling documents.
        with self._lock:
            self._conns.pop(cid, None)
            last = not self._conns and not self._handshakes
            ws = self._ws
            if ws is None or ws.closed:
                return
            if last:
                self._ws = None  # released: no new rider adopts it
        try:
            ws.send_text(json.dumps(
                {"type": "disconnect_document", "cid": cid}))
            if last:
                # Last rider gone: release the physical socket (odsp
                # socket-reference refcount reaching zero).
                ws.send_text(json.dumps({"type": "disconnect"}))
                ws.close()
        except (websocket.WebSocketClosed, OSError):
            pass

    def _read_loop(self, ws: websocket.WebSocketConnection) -> None:
        try:
            while True:
                frame = json.loads(ws.recv())
                cid = frame.get("cid")
                ftype = frame.get("type")
                if ftype in ("connected", "connect_error", "error"):
                    # Generic "error" frames settle a pending handshake on
                    # the same cid too (an older/foreign server answering a
                    # failed connect_document that way must fail the
                    # connect fast, not let it sit out the 30s timeout).
                    with self._lock:
                        handshake = self._handshakes.get(cid)
                    if handshake is not None:
                        handshake.resolve(frame)
                        continue
                    if ftype != "error":
                        continue
                with self._lock:
                    conn = self._conns.get(cid)
                if conn is None:
                    continue
                try:
                    conn._dispatch(frame)
                except Exception:  # noqa: BLE001 — isolate per document
                    # Mirror the legacy per-doc reader (and the server's
                    # per-cid isolation): a failing op handler (RestError
                    # on catch-up, malformed contents) drops THAT document
                    # — its container reconnects — never its siblings.
                    with self._lock:
                        self._conns.pop(cid, None)
                    try:
                        # Release the server side too, or the document
                        # stays joined (ghost client in the quorum) for
                        # the shared socket's lifetime.
                        self.send({"type": "disconnect_document",
                                   "cid": cid})
                    except (ConnectionError, OSError):
                        pass  # half-dead socket: its teardown handles it
                    conn._on_socket_dead()
        except (websocket.WebSocketClosed, OSError,
                json.JSONDecodeError, ValueError):
            pass
        finally:
            # Scope cleanup to riders of THIS socket: a replacement socket
            # may already be live with its own registrations.
            with self._lock:
                dead_conns = [c for c in self._conns.values()
                              if c._sock is ws]
                dead_handshakes = [h for h in self._handshakes.values()
                                   if getattr(h, "sock", None) is ws]
                for c in dead_conns:
                    self._conns.pop(c._cid, None)
                self._handshakes = {
                    cid: h for cid, h in self._handshakes.items()
                    if getattr(h, "sock", None) is not ws}
                if self._ws is ws:
                    self._ws = None
            for handshake in dead_handshakes:
                handshake.reject(ConnectionError("mux socket closed"))
            for conn in dead_conns:
                conn._on_socket_dead()


class MuxConnectionPool:
    """Socket managers keyed by endpoint — the factory-level registry that
    makes two documents on the same endpoint share one socket."""

    def __init__(self):
        self._managers: Dict[Tuple[str, int, str], MuxSocketManager] = {}
        self._lock = threading.Lock()

    def manager(self, host: str, port: int,
                path: str = "/socket-mux") -> MuxSocketManager:
        key = (host, port, path)
        with self._lock:
            mgr = self._managers.get(key)
            if mgr is None:
                mgr = MuxSocketManager(host, port, path)
                self._managers[key] = mgr
            return mgr
