"""Isolation proxy driver: the whole driver interface over a serialized
message boundary.

Capability parity with reference packages/drivers/iframe-driver (801 LoC:
`innerDocumentService.ts` / `outerDocumentServiceFactory.ts` — the driver
proxied across an iframe via comlink/postMessage so untrusted app code
never holds service credentials): here the boundary is a pair of transport
callables carrying ONLY JSON-serializable dicts. The host side
(`DriverProxyHost`) owns the real driver; the sandboxed side
(`ProxyDocumentService`) implements the full `IDocumentService` contract by
request/response messages, with sequenced ops pushed as serialized events.
Wrap the transport in json round-trips (as the tests do) and the isolation
is machine-checked.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional

from ...core.events import TypedEventEmitter
from ...protocol.messages import (DocumentMessage,
                                  SequencedDocumentMessage, SignalMessage)
from ...protocol.summary import (summary_tree_from_dict,
                                 summary_tree_to_dict)
from .base import (IDocumentDeltaConnection, IDocumentDeltaStorageService,
                   IDocumentService, IDocumentServiceFactory,
                   IDocumentStorageService)
from .file import message_from_json, message_to_json


def _doc_message_to_json(m: DocumentMessage) -> dict:
    return {"clientSequenceNumber": m.client_sequence_number,
            "referenceSequenceNumber": m.reference_sequence_number,
            "type": m.type, "contents": m.contents, "data": m.data}


def _doc_message_from_json(d: dict) -> DocumentMessage:
    return DocumentMessage(
        client_sequence_number=d["clientSequenceNumber"],
        reference_sequence_number=d["referenceSequenceNumber"],
        type=d["type"], contents=d.get("contents"), data=d.get("data"))


class DriverProxyHost:
    """The privileged side (reference OuterDocumentServiceFactory): holds
    the real factory; executes serialized requests; pushes connection
    events outward through `event_sink(event_dict)` callables registered
    per connection id."""

    def __init__(self, factory: IDocumentServiceFactory):
        self.factory = factory
        self._services: Dict[str, IDocumentService] = {}
        self._connections: Dict[int, Any] = {}
        self._conn_ids = itertools.count(1)
        self._event_sinks: Dict[int, Callable[[dict], None]] = {}
        self._lock = threading.RLock()

    def set_event_sink(self, conn_id: int,
                       sink: Callable[[dict], None]) -> None:
        with self._lock:
            self._event_sinks[conn_id] = sink

    def _service(self, document_id: str) -> IDocumentService:
        with self._lock:
            if document_id not in self._services:
                self._services[document_id] = \
                    self.factory.create_document_service(document_id)
            return self._services[document_id]

    # -- the single request entry point ------------------------------------
    def handle(self, request: dict) -> dict:
        """Execute one serialized driver request; returns a serializable
        response. Errors come back as {"error", "kind"} (comlink's thrown-
        error marshalling)."""
        try:
            return {"result": self._dispatch(request)}
        except FileNotFoundError as exc:
            return {"error": str(exc), "kind": "notFound"}
        except PermissionError as exc:
            return {"error": str(exc), "kind": "permission"}
        except Exception as exc:  # noqa: BLE001 — marshal, don't leak
            return {"error": repr(exc), "kind": "generic"}

    def _dispatch(self, request: dict):
        op = request["op"]
        doc = request.get("documentId", "")
        if op == "getSummary":
            summary = self._service(doc).connect_to_storage().get_summary(
                request.get("version"))
            return None if summary is None else summary_tree_to_dict(summary)
        if op == "uploadSummary":
            handle = self._service(doc).connect_to_storage().upload_summary(
                summary_tree_from_dict(request["summary"]),
                parent=request.get("parent"),
                initial=request.get("initial", False))
            return handle
        if op == "getVersions":
            return self._service(doc).connect_to_storage().get_versions(
                request.get("count", 1))
        if op == "getDeltas":
            msgs = self._service(doc).connect_to_delta_storage().get(
                request.get("fromSeq", 0), request.get("toSeq"))
            return [message_to_json(m) for m in msgs]
        if op == "connect":
            conn = self._service(doc).connect_to_delta_stream(
                request.get("clientDetails"))
            conn_id = next(self._conn_ids)
            with self._lock:
                self._connections[conn_id] = conn
            conn.on("op", lambda m, cid=conn_id: self._push(
                cid, {"event": "op", "message": message_to_json(m)}))
            conn.on("nack", lambda n, cid=conn_id: self._push(
                cid, {"event": "nack", "nack": n if isinstance(n, dict)
                      else {"content": str(n)}}))
            conn.on("signal", lambda s, cid=conn_id: self._push(
                cid, {"event": "signal", "clientId": s.client_id,
                      "content": s.content}))
            conn.on("disconnect", lambda cid=conn_id: self._push(
                cid, {"event": "disconnect"}))
            return {"connectionId": conn_id, "clientId": conn.client_id}
        if op == "submit":
            conn = self._connections[request["connectionId"]]
            conn.submit([_doc_message_from_json(d)
                         for d in request["messages"]])
            return True
        if op == "submitSignal":
            conn = self._connections[request["connectionId"]]
            conn.submit_signal(request.get("content"))
            return True
        if op == "closeConnection":
            conn = self._connections.pop(request["connectionId"], None)
            if conn is not None:
                conn.close()
            return True
        raise ValueError(f"unknown driver op {op!r}")

    def _push(self, conn_id: int, event: dict) -> None:
        sink = self._event_sinks.get(conn_id)
        if sink is not None:
            sink(event)


# -- sandboxed side --------------------------------------------------------
class ProxyStorageService(IDocumentStorageService):
    def __init__(self, call, document_id: str):
        self._call = call
        self.document_id = document_id

    def get_summary(self, version: Optional[str] = None):
        data = self._call({"op": "getSummary", "documentId": self.document_id,
                           "version": version})
        return None if data is None else summary_tree_from_dict(data)

    def upload_summary(self, summary, parent=None, initial=False) -> str:
        return self._call({"op": "uploadSummary",
                           "documentId": self.document_id,
                           "summary": summary_tree_to_dict(summary),
                           "parent": parent, "initial": initial})

    def get_versions(self, count: int = 1) -> List[str]:
        return self._call({"op": "getVersions",
                           "documentId": self.document_id, "count": count})


class ProxyDeltaStorage(IDocumentDeltaStorageService):
    def __init__(self, call, document_id: str):
        self._call = call
        self.document_id = document_id

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        rows = self._call({"op": "getDeltas", "documentId": self.document_id,
                           "fromSeq": from_seq, "toSeq": to_seq})
        return [message_from_json(d) for d in rows]


class ProxyDeltaConnection(TypedEventEmitter, IDocumentDeltaConnection):
    def __init__(self, call, document_id: str,
                 register_sink: Callable[[int, Callable[[dict], None]], None],
                 client_details: Optional[dict]):
        TypedEventEmitter.__init__(self)
        self._call = call
        info = call({"op": "connect", "documentId": document_id,
                     "clientDetails": client_details})
        self.connection_id = info["connectionId"]
        self.client_id = info["clientId"]
        register_sink(self.connection_id, self._on_event)

    def _on_event(self, event: dict) -> None:
        kind = event["event"]
        if kind == "op":
            self.emit("op", message_from_json(event["message"]))
        elif kind == "nack":
            self.emit("nack", event.get("nack"))
        elif kind == "signal":
            self.emit("signal", SignalMessage(
                client_id=event.get("clientId"),
                content=event.get("content")))
        elif kind == "disconnect":
            self.emit("disconnect")

    def submit(self, messages: List[DocumentMessage]) -> None:
        self._call({"op": "submit", "connectionId": self.connection_id,
                    "messages": [_doc_message_to_json(m) for m in messages]})

    def submit_signal(self, content) -> None:
        self._call({"op": "submitSignal", "connectionId": self.connection_id,
                    "content": content})

    def close(self) -> None:
        self._call({"op": "closeConnection",
                    "connectionId": self.connection_id})


class ProxyDocumentService(IDocumentService):
    def __init__(self, transport: Callable[[dict], dict], document_id: str,
                 register_sink: Callable[[int, Callable[[dict], None]], None]):
        self.transport = transport
        self.document_id = document_id
        self.register_sink = register_sink

    def _call(self, request: dict):
        response = self.transport(request)
        if "error" in response:
            kind = response.get("kind")
            if kind == "notFound":
                raise FileNotFoundError(response["error"])
            if kind == "permission":
                raise PermissionError(response["error"])
            raise RuntimeError(response["error"])
        return response.get("result")

    def connect_to_storage(self):
        return ProxyStorageService(self._call, self.document_id)

    def connect_to_delta_storage(self):
        return ProxyDeltaStorage(self._call, self.document_id)

    def connect_to_delta_stream(self, client_details: Optional[dict] = None):
        return ProxyDeltaConnection(self._call, self.document_id,
                                    self.register_sink, client_details)


class ProxyDocumentServiceFactory(IDocumentServiceFactory):
    """The sandboxed factory (reference InnerDocumentServiceFactory). Built
    from a request transport + an event-sink registrar — in tests both sides
    of a `DriverProxyHost` with json.dumps round-trips in between."""

    def __init__(self, transport: Callable[[dict], dict],
                 register_sink: Callable[[int, Callable[[dict], None]],
                                         None]):
        self.transport = transport
        self.register_sink = register_sink

    @staticmethod
    def over_host(host: DriverProxyHost,
                  codec: Optional[Callable[[dict], dict]] = None
                  ) -> "ProxyDocumentServiceFactory":
        """Wire directly to a host, optionally forcing every payload
        through `codec` (e.g. a json round-trip) in both directions."""
        codec = codec or (lambda d: d)

        def transport(request: dict) -> dict:
            return codec(host.handle(codec(request)))

        def register_sink(conn_id: int, sink: Callable[[dict], None]):
            host.set_event_sink(conn_id, lambda event: sink(codec(event)))

        return ProxyDocumentServiceFactory(transport, register_sink)

    def create_document_service(self, document_id: str) -> IDocumentService:
        return ProxyDocumentService(self.transport, document_id,
                                    self.register_sink)
