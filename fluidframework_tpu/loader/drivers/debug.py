"""Debugger driver: step through op history under manual control.

Capability parity with reference packages/drivers/debugger
(fluidDebuggerController.ts): wraps any document service; inbound sequenced
ops are held in a queue and released N at a time (or all), letting a human
(or test) inspect intermediate document states."""

from __future__ import annotations

from typing import List, Optional

from ...core.events import TypedEventEmitter
from .base import (
    IDocumentDeltaConnection,
    IDocumentService,
    IDocumentServiceFactory,
)


class DebugController:
    """step(n)/go() gate op delivery (reference DebuggerUI buttons)."""

    def __init__(self, paused: bool = True):
        self.paused = paused
        self._connections: List["DebugDeltaConnection"] = []

    def step(self, count: int = 1) -> int:
        released = 0
        for conn in self._connections:
            released += conn.release(count)
        return released

    def go(self) -> None:
        self.paused = False
        for conn in self._connections:
            conn.release(None)

    def pause(self) -> None:
        self.paused = True


class DebugDeltaConnection(TypedEventEmitter, IDocumentDeltaConnection):
    def __init__(self, inner: IDocumentDeltaConnection,
                 controller: DebugController):
        TypedEventEmitter.__init__(self)
        self.inner = inner
        self.client_id = inner.client_id
        self.controller = controller
        self._held: List = []
        controller._connections.append(self)
        inner.on("op", self._on_op)
        inner.on("nack", lambda n: self.emit("nack", n))
        inner.on("signal", lambda s: self.emit("signal", s))
        inner.on("disconnect", lambda: self.emit("disconnect"))

    def _on_op(self, message) -> None:
        if self.controller.paused:
            self._held.append(message)
        else:
            self.emit("op", message)

    def release(self, count: Optional[int]) -> int:
        n = len(self._held) if count is None else min(count, len(self._held))
        for _ in range(n):
            self.emit("op", self._held.pop(0))
        return n

    @property
    def held_count(self) -> int:
        return len(self._held)

    def submit(self, messages) -> None:
        self.inner.submit(messages)

    def submit_signal(self, content) -> None:
        self.inner.submit_signal(content)

    def close(self) -> None:
        self.inner.close()


class DebugDocumentService(IDocumentService):
    def __init__(self, inner: IDocumentService, controller: DebugController):
        self.inner = inner
        self.controller = controller

    def connect_to_storage(self):
        return self.inner.connect_to_storage()

    def connect_to_delta_storage(self):
        return self.inner.connect_to_delta_storage()

    def connect_to_delta_stream(self, client_details=None):
        return DebugDeltaConnection(
            self.inner.connect_to_delta_stream(client_details),
            self.controller)


class DebugDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, inner: IDocumentServiceFactory,
                 controller: Optional[DebugController] = None):
        self.inner = inner
        self.controller = controller or DebugController()

    def create_document_service(self, document_id: str) -> IDocumentService:
        return DebugDocumentService(
            self.inner.create_document_service(document_id), self.controller)
