"""Local driver: the in-process driver onto LocalServer (reference
packages/drivers/local-driver — the test backbone, SURVEY.md §2.3)."""

from __future__ import annotations

from typing import List, Optional

from ...protocol.messages import ITrace, SequencedDocumentMessage
from ...protocol.summary import SummaryTree
from ...server.local_server import LocalServer
from ...telemetry import tracing
from .base import (
    IDocumentDeltaConnection,
    IDocumentDeltaStorageService,
    IDocumentService,
    IDocumentServiceFactory,
    IDocumentStorageService,
)


def _row_to_message(row: dict) -> SequencedDocumentMessage:
    return SequencedDocumentMessage(
        client_id=row["client_id"],
        sequence_number=row["sequence_number"],
        minimum_sequence_number=row["minimum_sequence_number"],
        client_sequence_number=row["client_sequence_number"],
        reference_sequence_number=row["reference_sequence_number"],
        type=row["type"],
        contents=row["contents"],
        metadata=row.get("metadata"),
        server_metadata=row.get("server_metadata"),
        timestamp=row.get("timestamp", 0.0),
        traces=[ITrace(**t) if isinstance(t, dict) else t
                for t in row.get("traces", [])],
        data=row.get("data"),
    )


class LocalDocumentStorageService(IDocumentStorageService):
    def __init__(self, server: LocalServer, document_id: str):
        self.server = server
        self.document_id = document_id
        self.store = server.storage(document_id)

    def get_summary(self, version: Optional[str] = None):
        # Reads ride the historian cache (reference: drivers talk to
        # historian, the caching proxy, never to gitrest directly).
        # lazy: blob contents resolve on first access, so a lazy-loading
        # channel (sequence body chunks) defers their transfer entirely.
        return self.server.historian.read_summary(
            self.server.tenant_id, self.document_id, commit_sha=version,
            lazy=True)

    def upload_summary(self, summary: SummaryTree,
                       parent: Optional[str] = None,
                       initial: bool = False) -> str:
        """initial=True is the attach summary: it becomes the load target
        immediately (no scribe in the loop yet). Later uploads are proposals;
        scribe advances the ref on summaryAck."""
        return self.store.write_summary(summary, base_commit=parent,
                                        advance_ref=initial)

    def get_versions(self, count: int = 1) -> List[str]:
        return [c.sha for c in self.store.list_commits(limit=count)]

    def get_catchup(self):
        """summary + delta in one call (in-process, so the `one round
        trip` is literal: both halves resolve against the same server
        under one lock round)."""
        artifact = self.server.get_catchup(self.document_id)
        summary = None
        if artifact is not None and artifact.get("summarySha"):
            # Load the EXACT summary the artifact was published against:
            # a client summary committed after the refresh would
            # otherwise race ahead of the delta's baseline.
            summary = self.server.historian.read_summary(
                self.server.tenant_id, self.document_id,
                commit_sha=artifact["summarySha"], lazy=True)
        if summary is None:
            summary = self.get_summary()
        return summary, artifact

    def get_catchup_artifact(self):
        return self.server.get_catchup(self.document_id)


class LocalDeltaStorageService(IDocumentDeltaStorageService):
    def __init__(self, server: LocalServer, document_id: str):
        self.server = server
        self.document_id = document_id

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        rows = self.server.get_deltas(self.document_id, from_seq, to_seq)
        return [_row_to_message(r) for r in rows]


class LocalDocumentDeltaConnection(IDocumentDeltaConnection):
    def __init__(self, server: LocalServer, document_id: str,
                 client_details: Optional[dict]):
        self._conn = server.connect(document_id, client_details)
        self.client_id = self._conn.client_id

    def submit(self, messages) -> None:
        # Adopt the context the client edit minted (same thread) — or
        # mint one here — and put it on the wire: metadata rides the
        # envelope end to end.
        ctx = tracing.ensure_op_context()
        if ctx is not None:
            for msg in messages:
                tracing.stamp_message(msg, ctx)
        with tracing.span("driver.submit", parent=ctx,
                          count=len(messages)):
            self._conn.submit(messages)

    def submit_signal(self, content) -> None:
        self._conn.submit_signal(content)

    def on(self, event, fn) -> None:
        self._conn.on(event, fn)

    def off(self, event, fn) -> None:
        self._conn.off(event, fn)

    def close(self) -> None:
        self._conn.disconnect()


class LocalDocumentService(IDocumentService):
    def __init__(self, server: LocalServer, document_id: str):
        self.server = server
        self.document_id = document_id

    def connect_to_storage(self):
        return LocalDocumentStorageService(self.server, self.document_id)

    def connect_to_delta_storage(self):
        return LocalDeltaStorageService(self.server, self.document_id)

    def connect_to_delta_stream(self, client_details=None):
        return LocalDocumentDeltaConnection(self.server, self.document_id,
                                            client_details)


class LocalDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, server: LocalServer):
        self.server = server

    def create_document_service(self, document_id: str) -> IDocumentService:
        return LocalDocumentService(self.server, document_id)
