"""Caching driver: production-style snapshot cache + token refresh
wrapper over any inner driver.

Capability parity with reference packages/drivers/odsp-driver (6,713 LoC)
— the production driver's value-adds over plain REST: a **persistent
snapshot cache** (load from cached summary + fetch only the op tail;
write-through on summary upload; epoch-guarded invalidation when the
service's version moved), **token fetch with refresh-on-auth-failure**,
and **connection multiplexing** (one shared transport serving several
documents). The reference binds these to SPO specifics; here they decorate
any `IDocumentServiceFactory`.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ...protocol.messages import SequencedDocumentMessage
from ...protocol.summary import (SummaryHandle, SummaryTree,
                                 summary_tree_from_dict,
                                 summary_tree_to_dict)
from .base import (IDocumentDeltaStorageService, IDocumentService,
                   IDocumentServiceFactory, IDocumentStorageService)
from .file import message_from_json, message_to_json


def _has_handles(node) -> bool:
    """True when an (incremental) summary tree contains SummaryHandles —
    such a tree is not self-contained and must not be cached as a load
    source."""
    if isinstance(node, SummaryHandle):
        return True
    if isinstance(node, SummaryTree):
        return any(_has_handles(child) for child in node.entries.values())
    return False


class PersistentCache:
    """Snapshot cache (reference odsp persistedCache): per document key
    stores {version, summary, ops} — the summary plus the op tail collected
    since. File-backed when a directory is given, else in-memory."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._mem: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _path(self, key: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in key)
        return os.path.join(self.directory, f"{safe}.json")

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            if self.directory:
                try:
                    with open(self._path(key)) as f:
                        entry = json.load(f)
                except FileNotFoundError:
                    entry = None
            else:
                entry = self._mem.get(key)
            if entry is None:
                self.misses += 1
            else:
                self.hits += 1
            return entry

    def put(self, key: str, entry: dict) -> None:
        with self._lock:
            if self.directory:
                with open(self._path(key), "w") as f:
                    json.dump(entry, f)
            else:
                self._mem[key] = entry

    def remove(self, key: str) -> None:
        with self._lock:
            if self.directory:
                try:
                    os.remove(self._path(key))
                except FileNotFoundError:
                    pass
            else:
                self._mem.pop(key, None)


class CachingStorageService(IDocumentStorageService):
    """Serves get_summary from cache when the service's head version still
    matches (epoch guard); write-through on upload."""

    def __init__(self, inner: IDocumentStorageService, cache: PersistentCache,
                 key: str):
        self.inner = inner
        self.cache = cache
        self.key = key

    def get_summary(self, version: Optional[str] = None):
        if version is not None:
            # Explicit historical version: bypass the cache entirely (the
            # cache only ever holds the document head).
            return self.inner.get_summary(version)
        entry = self.cache.get(self.key)
        versions = self.inner.get_versions(1)
        head = versions[0] if versions else None
        if entry is not None and entry.get("version") == head:
            return summary_tree_from_dict(entry["summary"])
        # Epoch moved (another client summarized) or cold: refresh.
        self.cache.remove(self.key)
        summary = self.inner.get_summary()
        if summary is not None:
            self.cache.put(self.key, {
                "version": head,
                "summary": summary_tree_to_dict(summary),
                "ops": []})
        return summary

    def upload_summary(self, summary: SummaryTree, parent=None,
                       initial: bool = False) -> str:
        handle = self.inner.upload_summary(summary, parent=parent,
                                           initial=initial)
        if _has_handles(summary):
            # An incremental upload is NOT a full tree (handles resolve
            # server-side at write time); caching it would serve a
            # handle-bearing tree to the next boot's load. Drop the entry
            # — get_summary refetches the resolved tree on demand.
            self.cache.remove(self.key)
        else:
            self.cache.put(self.key, {
                "version": handle,
                "summary": summary_tree_to_dict(summary),
                "ops": []})
        return handle

    def get_versions(self, count: int = 1) -> List[str]:
        return self.inner.get_versions(count)


class CachingDeltaStorage(IDocumentDeltaStorageService):
    """Appends fetched ops to the cache entry so the next boot replays the
    tail without refetching (reference odsp opsCache)."""

    def __init__(self, inner: IDocumentDeltaStorageService,
                 cache: PersistentCache, key: str):
        self.inner = inner
        self.cache = cache
        self.key = key

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        entry = self.cache.get(self.key)
        # Only the CONTIGUOUS cached run starting at from_seq+1 is usable —
        # a cached tail beyond a hole (e.g. ops that arrived over the live
        # stream and were never cached) must not mask the hole.
        cached: List[SequencedDocumentMessage] = []
        if entry is not None:
            run = sorted((message_from_json(d) for d in entry.get("ops", [])
                          if d["sequenceNumber"] > from_seq
                          and (to_seq is None
                               or d["sequenceNumber"] <= to_seq)),
                         key=lambda m: m.sequence_number)
            expect = from_seq + 1
            for m in run:
                if m.sequence_number != expect:
                    break
                cached.append(m)
                expect += 1
        start = cached[-1].sequence_number if cached else from_seq
        fetched = self.inner.get(start, to_seq)
        if fetched and entry is not None:
            known = {d["sequenceNumber"] for d in entry.get("ops", [])}
            entry.setdefault("ops", []).extend(
                message_to_json(m) for m in fetched
                if m.sequence_number not in known)
            self.cache.put(self.key, entry)
        return cached + fetched


class TokenRefreshWrapper:
    """Token fetch + refresh-on-failure (reference odsp tokenFetcher):
    `token_provider(refresh: bool)` returns a token; an auth failure in the
    wrapped call triggers one forced-refresh retry."""

    def __init__(self, token_provider: Callable[[bool], str]):
        self.token_provider = token_provider
        self._token: Optional[str] = None

    def token(self, refresh: bool = False) -> str:
        if self._token is None or refresh:
            self._token = self.token_provider(refresh)
        return self._token

    def call(self, fn: Callable[[str], object]):
        try:
            return fn(self.token())
        except PermissionError:
            return fn(self.token(refresh=True))


class CachingDocumentService(IDocumentService):
    def __init__(self, inner: IDocumentService, cache: PersistentCache,
                 key: str):
        self.inner = inner
        self.cache = cache
        self.key = key

    def connect_to_storage(self):
        return CachingStorageService(self.inner.connect_to_storage(),
                                     self.cache, self.key)

    def connect_to_delta_storage(self):
        return CachingDeltaStorage(self.inner.connect_to_delta_storage(),
                                   self.cache, self.key)

    def connect_to_delta_stream(self, client_details: Optional[dict] = None):
        # The live stream always goes to the real service (multiplexing
        # happens below this layer in the shared transport).
        return self.inner.connect_to_delta_stream(client_details)


class CachingDocumentServiceFactory(IDocumentServiceFactory):
    """Decorates any factory with the persistent cache. One factory = one
    cache = one shared transport namespace, mirroring the odsp driver's
    one-socket-many-documents multiplexing shape.

    historian_url composes the client cache with the SERVER-side cache
    tier (server/historian.py): the inner factory's storage endpoint
    repoints at the tier, so even this cache's epoch-check misses (head
    moved, cold boot) serve their blobs from the historian instead of
    GitStore."""

    def __init__(self, inner: IDocumentServiceFactory,
                 cache: Optional[PersistentCache] = None,
                 historian_url: Optional[str] = None):
        self.inner = inner
        self.cache = cache or PersistentCache()
        self.historian_url = historian_url
        if historian_url is not None:
            set_endpoint = getattr(inner, "set_historian_endpoint", None)
            if set_endpoint is None:
                raise TypeError(
                    f"{type(inner).__name__} does not support a historian "
                    "endpoint (no set_historian_endpoint)")
            set_endpoint(historian_url)
        self._services: Dict[str, CachingDocumentService] = {}

    def create_document_service(self, document_id: str) -> IDocumentService:
        if document_id not in self._services:
            self._services[document_id] = CachingDocumentService(
                self.inner.create_document_service(document_id),
                self.cache, document_id)
        return self._services[document_id]
