"""Production resilience wrappers: retry/backoff, throttle honoring,
single-flight fetch dedup, snapshot prefetch.

Capability parity with the reference odsp-driver's network hardening
(packages/drivers/odsp-driver: retryAndConvertToNetworkError, throttling
(429 retryAfter) handling, prefetchSnapshot, concurrent fetch dedup) —
decorating any `IDocumentServiceFactory`, usually stacked OUTSIDE the
caching driver:

    factory = RetryingDocumentServiceFactory(
        CachingDocumentServiceFactory(inner, cache), policy)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ...telemetry.counters import record_swallow
from .base import (IDocumentDeltaStorageService, IDocumentService,
                   IDocumentServiceFactory, IDocumentStorageService)


class ThrottlingError(Exception):
    """Service asked the client to back off (reference 429 retryAfter)."""

    def __init__(self, retry_after_s: float, message: str = "throttled"):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class NonRetryableError(Exception):
    """Fatal service response: retrying cannot help (4xx-class)."""


class RetryPolicy:
    """Exponential backoff with full jitter, capped attempts/delay; a
    ThrottlingError's retry_after overrides the computed delay."""

    def __init__(self, max_attempts: int = 5, base_delay_s: float = 0.05,
                 max_delay_s: float = 8.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.sleep = sleep
        self.rng = rng or random.Random()

    def run(self, fn: Callable[[], object], on_retry=None):
        attempt = 0
        while True:
            try:
                return fn()
            except NonRetryableError:
                raise
            except ThrottlingError as err:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                delay = min(err.retry_after_s, self.max_delay_s)
            except Exception:  # noqa: BLE001 — transient service failure
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                cap = min(self.max_delay_s,
                          self.base_delay_s * (2 ** (attempt - 1)))
                delay = self.rng.uniform(0, cap)  # full jitter
            if on_retry is not None:
                on_retry(attempt, delay)
            self.sleep(delay)


class SingleFlight:
    """Concurrent identical fetches collapse into one in-flight call
    (reference odsp snapshot fetch dedup)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: Dict[str, threading.Event] = {}
        self._results: Dict[str, object] = {}

    def do(self, key: str, fn: Callable[[], object]):
        with self._lock:
            event = self._inflight.get(key)
            if event is None:
                event = threading.Event()
                self._inflight[key] = event
                leader = True
            else:
                leader = False
        if not leader:
            event.wait()
            outcome = self._results[key]
            if isinstance(outcome, BaseException):
                raise outcome
            return outcome
        try:
            result = fn()
            outcome: object = result
        except BaseException as err:  # propagate to followers too
            outcome = err
            raise
        finally:
            with self._lock:
                self._results[key] = outcome
                del self._inflight[key]
            event.set()
        return result


class RetryingStorageService(IDocumentStorageService):
    def __init__(self, inner: IDocumentStorageService, policy: RetryPolicy,
                 flight: SingleFlight, key: str):
        self.inner = inner
        self.policy = policy
        self.flight = flight
        self.key = key

    def get_summary(self, version: Optional[str] = None):
        flight_key = f"{self.key}:summary:{version}"
        return self.flight.do(flight_key, lambda: self.policy.run(
            lambda: self.inner.get_summary(version)))

    def upload_summary(self, summary, parent=None, initial: bool = False):
        # Uploads are NOT single-flighted (each is a distinct mutation).
        return self.policy.run(lambda: self.inner.upload_summary(
            summary, parent=parent, initial=initial))

    def get_versions(self, count: int = 1) -> List[str]:
        return self.policy.run(lambda: self.inner.get_versions(count))


class RetryingDeltaStorage(IDocumentDeltaStorageService):
    def __init__(self, inner: IDocumentDeltaStorageService,
                 policy: RetryPolicy):
        self.inner = inner
        self.policy = policy

    def get(self, from_seq: int, to_seq: Optional[int] = None):
        return self.policy.run(lambda: self.inner.get(from_seq, to_seq))


class RetryingDocumentService(IDocumentService):
    def __init__(self, inner: IDocumentService, policy: RetryPolicy,
                 flight: SingleFlight, key: str):
        self.inner = inner
        self.policy = policy
        self.flight = flight
        self.key = key

    def connect_to_storage(self):
        return RetryingStorageService(self.inner.connect_to_storage(),
                                      self.policy, self.flight, self.key)

    def connect_to_delta_storage(self):
        return RetryingDeltaStorage(self.inner.connect_to_delta_storage(),
                                    self.policy)

    def connect_to_delta_stream(self, client_details: Optional[dict] = None):
        # Connection attempts retry too (reference reconnect backoff).
        return self.policy.run(
            lambda: self.inner.connect_to_delta_stream(client_details))


class RetryingDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, inner: IDocumentServiceFactory,
                 policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy or RetryPolicy()
        self.flight = SingleFlight()

    def create_document_service(self, document_id: str) -> IDocumentService:
        return RetryingDocumentService(
            self.inner.create_document_service(document_id), self.policy,
            self.flight, document_id)

    def prefetch_snapshot(self, document_id: str) -> bool:
        """Warm the (stacked) cache before a load (reference
        prefetchSnapshot): fetch the head summary through the full wrapper
        stack; returns False when the fetch permanently failed."""
        try:
            service = self.create_document_service(document_id)
            service.connect_to_storage().get_summary()
            return True
        except Exception:  # noqa: BLE001 — prefetch is best-effort
            record_swallow("driver.prefetch_snapshot")
            return False
