"""Production resilience wrappers: retry/backoff, throttle honoring,
single-flight fetch dedup, snapshot prefetch.

Capability parity with the reference odsp-driver's network hardening
(packages/drivers/odsp-driver: retryAndConvertToNetworkError, throttling
(429 retryAfter) handling, prefetchSnapshot, concurrent fetch dedup) —
decorating any `IDocumentServiceFactory`, usually stacked OUTSIDE the
caching driver:

    factory = RetryingDocumentServiceFactory(
        CachingDocumentServiceFactory(inner, cache), policy)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ...core.retry import (NonRetryableError, RetryPolicy,  # noqa: F401
                           SingleFlight, ThrottlingError)
from ...telemetry.counters import record_swallow
from .base import (IDocumentDeltaStorageService, IDocumentService,
                   IDocumentServiceFactory, IDocumentStorageService)

# ThrottlingError / NonRetryableError / RetryPolicy / SingleFlight live
# in core.retry (the server's broker client reuses them and server may
# not import loader); re-exported here unchanged — this module remains
# their canonical driver-facing import path.


class RetryingStorageService(IDocumentStorageService):
    def __init__(self, inner: IDocumentStorageService, policy: RetryPolicy,
                 flight: SingleFlight, key: str):
        self.inner = inner
        self.policy = policy
        self.flight = flight
        self.key = key

    def get_summary(self, version: Optional[str] = None):
        flight_key = f"{self.key}:summary:{version}"
        return self.flight.do(flight_key, lambda: self.policy.run(
            lambda: self.inner.get_summary(version)))

    def upload_summary(self, summary, parent=None, initial: bool = False):
        # Uploads are NOT single-flighted (each is a distinct mutation).
        return self.policy.run(lambda: self.inner.upload_summary(
            summary, parent=parent, initial=initial))

    def get_versions(self, count: int = 1) -> List[str]:
        return self.policy.run(lambda: self.inner.get_versions(count))


class RetryingDeltaStorage(IDocumentDeltaStorageService):
    def __init__(self, inner: IDocumentDeltaStorageService,
                 policy: RetryPolicy):
        self.inner = inner
        self.policy = policy

    def get(self, from_seq: int, to_seq: Optional[int] = None):
        return self.policy.run(lambda: self.inner.get(from_seq, to_seq))


class RetryingDocumentService(IDocumentService):
    def __init__(self, inner: IDocumentService, policy: RetryPolicy,
                 flight: SingleFlight, key: str):
        self.inner = inner
        self.policy = policy
        self.flight = flight
        self.key = key

    def connect_to_storage(self):
        return RetryingStorageService(self.inner.connect_to_storage(),
                                      self.policy, self.flight, self.key)

    def connect_to_delta_storage(self):
        return RetryingDeltaStorage(self.inner.connect_to_delta_storage(),
                                    self.policy)

    def connect_to_delta_stream(self, client_details: Optional[dict] = None):
        # Connection attempts retry too (reference reconnect backoff).
        return self.policy.run(
            lambda: self.inner.connect_to_delta_stream(client_details))


class RetryingDocumentServiceFactory(IDocumentServiceFactory):
    def __init__(self, inner: IDocumentServiceFactory,
                 policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy or RetryPolicy()
        self.flight = SingleFlight()

    def create_document_service(self, document_id: str) -> IDocumentService:
        return RetryingDocumentService(
            self.inner.create_document_service(document_id), self.policy,
            self.flight, document_id)

    def prefetch_snapshot(self, document_id: str) -> bool:
        """Warm the (stacked) cache before a load (reference
        prefetchSnapshot): fetch the head summary through the full wrapper
        stack; returns False when the fetch permanently failed."""
        try:
            service = self.create_document_service(document_id)
            service.connect_to_storage().get_summary()
            return True
        except Exception:  # noqa: BLE001 — prefetch is best-effort
            record_swallow("driver.prefetch_snapshot")
            return False
