"""Replay driver: a recorded op stream as a read-only live document.

Capability parity with reference packages/drivers/replay-driver
(replayController.ts, replayDocumentService.ts): wraps a snapshot + op list
(from any source — a live service's delta storage, a file-driver capture);
the "connection" delivers the recorded ops up to a controllable watermark
and rejects submission. Used for debugging and snapshot-regression replay
(replay-tool)."""

from __future__ import annotations

from typing import List, Optional

from ...core.events import TypedEventEmitter
from ...protocol.messages import SequencedDocumentMessage
from ...protocol.summary import SummaryTree
from .base import (
    IDocumentDeltaConnection,
    IDocumentDeltaStorageService,
    IDocumentService,
    IDocumentServiceFactory,
    IDocumentStorageService,
)


class ReplayController:
    """Chooses how far to replay (reference ReplayController). The service
    starts at `start_seq` and delivers through `replay_to` (advance with
    forward())."""

    def __init__(self, replay_to: Optional[int] = None):
        self.replay_to = replay_to  # None = everything
        self._connections: List["ReplayDeltaConnection"] = []

    def forward(self, to_seq: Optional[int] = None) -> None:
        """Advance the watermark and push newly-visible ops."""
        self.replay_to = to_seq
        for conn in self._connections:
            conn.push()

    def visible(self, msg: SequencedDocumentMessage) -> bool:
        return self.replay_to is None or \
            msg.sequence_number <= self.replay_to


class ReplayStorageService(IDocumentStorageService):
    def __init__(self, summary: Optional[SummaryTree]):
        self.summary = summary

    def get_summary(self, version: Optional[str] = None):
        return self.summary

    def upload_summary(self, summary, parent=None, initial=False) -> str:
        raise PermissionError("replay documents are read-only")

    def get_versions(self, count: int = 1) -> List[str]:
        return []


class ReplayDeltaStorage(IDocumentDeltaStorageService):
    def __init__(self, ops: List[SequencedDocumentMessage],
                 controller: ReplayController):
        self.ops = ops
        self.controller = controller

    def get(self, from_seq: int, to_seq: Optional[int] = None
            ) -> List[SequencedDocumentMessage]:
        out = [m for m in self.ops
               if m.sequence_number > from_seq
               and (to_seq is None or m.sequence_number <= to_seq)
               and self.controller.visible(m)]
        return sorted(out, key=lambda m: m.sequence_number)


class ReplayDeltaConnection(TypedEventEmitter, IDocumentDeltaConnection):
    """Read-only: client identity never joins; submits are rejected."""

    def __init__(self, ops: List[SequencedDocumentMessage],
                 controller: ReplayController):
        TypedEventEmitter.__init__(self)
        self.client_id = "replay-readonly"
        self.ops = ops
        self.controller = controller
        self._delivered = 0
        controller._connections.append(self)

    def submit(self, messages) -> None:
        raise PermissionError("replay documents are read-only")

    def submit_signal(self, content) -> None:
        raise PermissionError("replay documents are read-only")

    def push(self) -> None:
        while self._delivered < len(self.ops):
            msg = self.ops[self._delivered]
            if not self.controller.visible(msg):
                break
            self._delivered += 1
            self.emit("op", msg)

    def close(self) -> None:
        self.emit("disconnect")


class ReplayDocumentService(IDocumentService):
    def __init__(self, summary: Optional[SummaryTree],
                 ops: List[SequencedDocumentMessage],
                 controller: Optional[ReplayController] = None):
        self.summary = summary
        self.ops = sorted(ops, key=lambda m: m.sequence_number)
        self.controller = controller or ReplayController()

    def connect_to_storage(self):
        return ReplayStorageService(self.summary)

    def connect_to_delta_storage(self):
        return ReplayDeltaStorage(self.ops, self.controller)

    def connect_to_delta_stream(self, client_details=None):
        conn = ReplayDeltaConnection(self.ops, self.controller)
        return conn


class ReplayDocumentServiceFactory(IDocumentServiceFactory):
    """Builds replay services from a capture source: any object exposing
    get_summary()/get_ops() — e.g. FileDocumentCapture or a live service's
    storage pair."""

    def __init__(self, summary, ops, controller=None):
        self.summary = summary
        self.ops = ops
        self.controller = controller

    def create_document_service(self, document_id: str) -> IDocumentService:
        return ReplayDocumentService(self.summary, self.ops,
                                     self.controller)
