"""URL resolvers: application URL -> resolved document endpoint.

Capability parity with reference packages/drivers/*-urlResolver
(routerlicious-urlResolver/src, odsp-urlResolver): parse
fluid://host/tenant/document[/path] into {tenant_id, document_id, path},
which the loader hands to the matching document service factory."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional
from urllib.parse import urlparse


@dataclass
class ResolvedUrl:
    tenant_id: str
    document_id: str
    path: str
    endpoint: str  # ordering-service endpoint (host)
    url: str


class FluidUrlResolver:
    """fluid://<host>/<tenant>/<document>[/<path...>] (the routerlicious
    URL shape)."""

    SCHEMES = ("fluid", "http", "https")

    def __init__(self, default_tenant: str = "local"):
        self.default_tenant = default_tenant

    def resolve(self, url: str) -> ResolvedUrl:
        parsed = urlparse(url)
        if parsed.scheme not in self.SCHEMES:
            raise ValueError(f"unsupported scheme {parsed.scheme!r}")
        parts: List[str] = [p for p in parsed.path.split("/") if p]
        if not parts:
            raise ValueError(f"no document in url {url!r}")
        if len(parts) == 1:
            tenant, doc = self.default_tenant, parts[0]
            rest = []
        else:
            tenant, doc, *rest = parts
        return ResolvedUrl(tenant_id=tenant, document_id=doc,
                           path="/" + "/".join(rest),
                           endpoint=parsed.netloc, url=url)


class MultiUrlResolver:
    """First resolver that succeeds wins (reference MultiUrlResolver)."""

    def __init__(self, *resolvers):
        self.resolvers = list(resolvers)

    def resolve(self, url: str) -> ResolvedUrl:
        errors = []
        for resolver in self.resolvers:
            try:
                return resolver.resolve(url)
            except ValueError as err:
                errors.append(str(err))
        raise ValueError(f"no resolver handled {url!r}: {errors}")
