"""Shared collections + utilities (reference common-utils capability parity)."""

from .collections import Heap, RangeTracker, RedBlackTree, IntervalTree
from .events import TypedEventEmitter
from .trace import Trace
