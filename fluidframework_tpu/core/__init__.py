"""Shared collections + utilities (reference common-utils capability parity)."""

from .collections import Heap, RangeTracker, RedBlackTree, IntervalTree
from .config import ConfigProvider
from .errors import BulkApplyUnsupported
from .events import Deferred, TypedEventEmitter
from .trace import Trace
