"""Hi-resolution split timer (reference common-utils/src/trace.ts:12)."""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass
class TraceEvent:
    total_time_elapsed_ms: float
    duration_ms: float
    tick: float


class Trace:
    @staticmethod
    def start() -> "Trace":
        return Trace()

    def __init__(self):
        self.start_tick = time.perf_counter()
        self.last_tick = self.start_tick

    def trace(self) -> TraceEvent:
        current = time.perf_counter()
        event = TraceEvent(
            total_time_elapsed_ms=(current - self.start_tick) * 1000.0,
            duration_ms=(current - self.last_tick) * 1000.0,
            tick=current,
        )
        self.last_tick = current
        return event
