"""Host-platform forcing for tests, entrypoints, and tools.

The ambient environment may pin an accelerator plugin backend (e.g. the
axon TPU tunnel) via a site hook that registers it through jax.config at
interpreter start.  That has two consequences every caller must respect:

- ``JAX_PLATFORMS=cpu`` in the environment is NOT enough — the site hook's
  config registration beats the env var; only
  ``jax.config.update("jax_platforms", "cpu")`` after ``import jax`` wins.
- Probing real devices first is NOT safe — ``jax.devices()`` initializes
  the plugin backend, and if its tunnel is unreachable the init blocks
  forever in native code (SIGALRM does not land).

This module is the single implementation of the force-CPU-with-virtual-
devices recipe used by tests/conftest.py, __graft_entry__.py, and tools.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_host_platform(n_devices: int = 8) -> None:
    """Force the CPU backend with ``n_devices`` virtual devices.

    Must run BEFORE any jax backend initialization.  Safe to call whether
    or not jax is already imported.  If backends are already initialized
    with an incompatible platform/device count, raises RuntimeError with a
    clear message instead of silently running on the wrong backend.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG in flags:
        # Replace, don't defer: a stale count from an earlier run would
        # leave fewer virtual devices than the caller requires.
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}",
                       flags)
    else:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    initialized = False
    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except (ImportError, AttributeError):  # pragma: no cover - jax internals
        pass
    if initialized:
        if jax.default_backend() != "cpu" or len(jax.devices()) < n_devices:
            raise RuntimeError(
                "jax backends already initialized "
                f"({jax.default_backend()}, {len(jax.devices())} devices); "
                f"cannot force cpu x {n_devices} — call force_host_platform "
                "before any jax.devices()/jit use")
        return
    jax.config.update("jax_platforms", "cpu")


def enable_compile_cache(path: str = None) -> None:
    """Persistent XLA compilation cache shared by the test suite and
    bench.py: the same kernel shapes (scan×vmap per (capacity, T),
    catch-up buckets) recompile every process otherwise."""
    import os

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            path or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                   "/tmp/fluid_tpu_xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # fluidlint: disable=SWALLOWED_EXCEPTION — core/ is the bottom layer
    # and must not import telemetry; a missing XLA cache dir only costs
    # recompiles (cache is best-effort).
    except Exception:  # pragma: no cover
        pass
