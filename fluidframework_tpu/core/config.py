"""Layered configuration provider.

Capability parity with the reference's nconf-based config system
(server: `nconf` file+env config per service, routerlicious/config/
config.json; lambda plugins take an `nconf.Provider`,
services-core/src/lambdas.ts:56; client: ILoaderOptions /
IContainerRuntimeOptions, containerRuntime.ts:205-208).

Lookup is by dotted path over a stack of layers; later layers win:
defaults < file < environment < overrides. Environment variables use
`PREFIX__a__b=value` (double underscore as the path separator, nconf
style); values parse as JSON when possible, else stay strings.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def _dig(layer: Dict[str, Any], path: List[str]):
    node: Any = layer
    for part in path:
        if not isinstance(node, dict) or part not in node:
            return None, False
        node = node[part]
    return node, True


class ConfigProvider:
    def __init__(self, *layers: Dict[str, Any]):
        # Lowest priority first.
        self._layers: List[Dict[str, Any]] = [dict(l) for l in layers if l]

    @classmethod
    def from_sources(cls, defaults: Optional[dict] = None,
                     file_path: Optional[str] = None,
                     env_prefix: Optional[str] = None,
                     overrides: Optional[dict] = None) -> "ConfigProvider":
        layers: List[Dict[str, Any]] = []
        if defaults:
            layers.append(defaults)
        if file_path and os.path.exists(file_path):
            with open(file_path) as f:
                layers.append(json.load(f))
        if env_prefix:
            layers.append(cls._env_layer(env_prefix))
        if overrides:
            layers.append(overrides)
        return cls(*layers)

    @staticmethod
    def _env_layer(prefix: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        marker = prefix + "__"
        for key, raw in os.environ.items():
            if not key.startswith(marker):
                continue
            path = key[len(marker):].split("__")
            try:
                value = json.loads(raw)
            except (json.JSONDecodeError, ValueError):
                value = raw
            node = out
            for part in path[:-1]:
                node = node.setdefault(part, {})
            node[path[-1]] = value
        return out

    # -- lookup ------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        path = key.split(".") if key else []
        for layer in reversed(self._layers):
            value, found = _dig(layer, path)
            if found:
                return value
        return default

    def require(self, key: str) -> Any:
        sentinel = object()
        value = self.get(key, sentinel)
        if value is sentinel:
            raise KeyError(f"missing required config key {key!r}")
        return value

    def sub(self, prefix: str) -> "ConfigProvider":
        """A provider scoped to one subtree (lambda plugins get their own
        section, mirroring the reference's per-service nconf slices)."""
        sublayers = []
        path = prefix.split(".")
        for layer in self._layers:
            value, found = _dig(layer, path)
            if found and isinstance(value, dict):
                sublayers.append(value)
        return ConfigProvider(*sublayers)

    def with_overrides(self, overrides: Dict[str, Any]) -> "ConfigProvider":
        return ConfigProvider(*self._layers, overrides)
