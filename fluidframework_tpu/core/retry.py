"""Layer-free retry primitives: bounded-backoff policy, throttle
signaling, single-flight dedup.

These started life in `loader/drivers/resilience.py` (the odsp-driver
network-hardening parity surface, which re-exports them unchanged) but
belong in core: the server's broker client (`server/log_service.py
RemoteMessageLog`) reuses the same bounded-backoff reconnect for broker
restarts, and server may not import loader (loader sits ABOVE server in
the layer matrix — tools/layer_check.py)."""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional


class ThrottlingError(Exception):
    """Service asked the client to back off (reference 429 retryAfter)."""

    def __init__(self, retry_after_s: float, message: str = "throttled"):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class NonRetryableError(Exception):
    """Fatal service response: retrying cannot help (4xx-class)."""


class RetryPolicy:
    """Exponential backoff with full jitter, capped attempts/delay; a
    ThrottlingError's retry_after overrides the computed delay."""

    def __init__(self, max_attempts: int = 5, base_delay_s: float = 0.05,
                 max_delay_s: float = 8.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.sleep = sleep
        self.rng = rng or random.Random()

    def run(self, fn: Callable[[], object], on_retry=None):
        attempt = 0
        while True:
            try:
                return fn()
            except NonRetryableError:
                raise
            except ThrottlingError as err:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                delay = min(err.retry_after_s, self.max_delay_s)
            except Exception:  # noqa: BLE001 — transient service failure
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                cap = min(self.max_delay_s,
                          self.base_delay_s * (2 ** (attempt - 1)))
                delay = self.rng.uniform(0, cap)  # full jitter
            if on_retry is not None:
                on_retry(attempt, delay)
            self.sleep(delay)


class SingleFlight:
    """Concurrent identical fetches collapse into one in-flight call
    (reference odsp snapshot fetch dedup)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: Dict[str, threading.Event] = {}
        self._results: Dict[str, object] = {}

    def do(self, key: str, fn: Callable[[], object]):
        with self._lock:
            event = self._inflight.get(key)
            if event is None:
                event = threading.Event()
                self._inflight[key] = event
                leader = True
            else:
                leader = False
        if not leader:
            event.wait()
            outcome = self._results[key]
            if isinstance(outcome, BaseException):
                raise outcome
            return outcome
        try:
            result = fn()
            outcome: object = result
        except BaseException as err:  # propagate to followers too
            outcome = err
            raise
        finally:
            with self._lock:
                self._results[key] = outcome
                del self._inflight[key]
            event.set()
        return result
