"""Cross-layer error contracts (reference container-utils DataCorruptionError
shape: exception types shared across layers without creating import edges)."""


class BulkApplyUnsupported(Exception):
    """A channel cannot apply a given batch in bulk; the caller must fall
    back to per-op processing. Raisers guarantee channel state is untouched.
    The merge-tree engine's catchup.Unmodelable subclasses this."""
