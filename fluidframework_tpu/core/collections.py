"""Core collections (capability parity with reference common-utils heap,
common-utils rangeTracker, and merge-tree/src/collections.ts RedBlackTree /
IntervalTree — re-designed: we use Python's heapq and a sorted-list-backed
ordered map instead of hand-rolled red-black rotations; the *device-side*
equivalents of these structures are flat arrays in mergetree/kernel.py).
"""

from __future__ import annotations

import bisect
import heapq
import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, Iterator, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class Heap(Generic[T]):
    """Min-heap with arbitrary key and lazy removal (reference common-utils Heap;
    used like deli's ClientSequenceNumberManager heap). Each item has at most
    one live entry; remove/update tombstone the current entry by identity so a
    re-pushed item is never confused with its stale entry."""

    def __init__(self, key: Callable[[T], Any] = lambda x: x):
        self._key = key
        self._heap: List[List[Any]] = []  # [key, tiebreak, item, live]
        self._counter = itertools.count()
        self._entries: dict = {}  # id(item) -> live entry

    def push(self, item: T) -> None:
        self.remove(item)  # keep the at-most-one-live-entry invariant
        entry = [self._key(item), next(self._counter), item, True]
        self._entries[id(item)] = entry
        heapq.heappush(self._heap, entry)

    def peek(self) -> Optional[T]:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def pop(self) -> Optional[T]:
        self._prune()
        if not self._heap:
            return None
        entry = heapq.heappop(self._heap)
        self._entries.pop(id(entry[2]), None)
        return entry[2]

    def remove(self, item: T) -> None:
        entry = self._entries.pop(id(item), None)
        if entry is not None:
            entry[3] = False

    def update(self, item: T) -> None:
        """Re-key an item: tombstone its current entry, push a fresh one."""
        self.remove(item)
        self.push(item)

    def _prune(self) -> None:
        while self._heap and not self._heap[0][3]:
            heapq.heappop(self._heap)

    def __len__(self) -> int:
        return len(self._entries)


@dataclass
class RangeTracker:
    """Maps a monotonically increasing primary range onto a secondary range
    (reference common-utils rangeTracker — used to map sequence numbers to log
    offsets for checkpointing)."""

    ranges: List[Tuple[int, int]] = field(default_factory=list)  # (primary, secondary)

    def add(self, primary: int, secondary: int) -> None:
        if self.ranges and primary < self.ranges[-1][0]:
            raise ValueError("primary values must be non-decreasing")
        self.ranges.append((primary, secondary))

    def get(self, primary: int) -> int:
        """Secondary value for the closest primary <= the given one."""
        idx = bisect.bisect_right(self.ranges, (primary, float("inf"))) - 1
        if idx < 0:
            raise KeyError(primary)
        return self.ranges[idx][1]

    def update_base(self, primary: int) -> None:
        """Drop ranges below primary (checkpoint trim)."""
        idx = bisect.bisect_right(self.ranges, (primary, float("inf"))) - 1
        if idx > 0:
            self.ranges = self.ranges[idx:]


K = TypeVar("K")
V = TypeVar("V")


class RedBlackTree(Generic[K, V]):
    """Ordered map. Reference merge-tree keeps a hand-written red-black tree
    (collections.ts); a bisect-backed sorted array gives the same O(log n)
    search with simpler code and better cache behavior host-side."""

    def __init__(self):
        self._keys: List[K] = []
        self._vals: List[V] = []

    def put(self, key: K, value: V) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            self._vals[i] = value
        else:
            self._keys.insert(i, key)
            self._vals.insert(i, value)

    def get(self, key: K) -> Optional[V]:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            return self._vals[i]
        return None

    def remove(self, key: K) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]
            del self._vals[i]

    def floor(self, key: K) -> Optional[Tuple[K, V]]:
        i = bisect.bisect_right(self._keys, key) - 1
        return (self._keys[i], self._vals[i]) if i >= 0 else None

    def ceil(self, key: K) -> Optional[Tuple[K, V]]:
        i = bisect.bisect_left(self._keys, key)
        return (self._keys[i], self._vals[i]) if i < len(self._keys) else None

    def min(self) -> Optional[Tuple[K, V]]:
        return (self._keys[0], self._vals[0]) if self._keys else None

    def max(self) -> Optional[Tuple[K, V]]:
        return (self._keys[-1], self._vals[-1]) if self._keys else None

    def items(self) -> Iterator[Tuple[K, V]]:
        return iter(zip(list(self._keys), list(self._vals)))

    def __len__(self) -> int:
        return len(self._keys)


@dataclass(order=True)
class _Interval:
    start: int
    end: int
    data: Any = field(compare=False, default=None)


class IntervalTree:
    """Interval set with stabbing/overlap queries (reference
    merge-tree/src/collections.ts IntervalTree, backing interval collections).
    Sorted-by-start array: queries bisect to prune intervals starting after
    the query range, then filter the prefix by end (O(n) worst case when many
    intervals start early; fine for interval-collection sizes)."""

    def __init__(self):
        self._intervals: List[_Interval] = []

    def put(self, start: int, end: int, data: Any = None) -> _Interval:
        iv = _Interval(start, end, data)
        bisect.insort(self._intervals, iv)
        return iv

    def remove(self, iv: _Interval) -> None:
        i = bisect.bisect_left(self._intervals, iv)
        while i < len(self._intervals):
            if self._intervals[i] is iv:
                del self._intervals[i]
                return
            if self._intervals[i].start > iv.start:
                break
            i += 1

    def overlapping(self, start: int, end: int) -> List[_Interval]:
        # Prune everything starting after `end`; filter the prefix by end.
        hi = bisect.bisect_right(self._intervals, _Interval(end, 2**62))
        return [iv for iv in self._intervals[:hi] if start <= iv.end]

    def stab(self, point: int) -> List[_Interval]:
        return self.overlapping(point, point)

    def __iter__(self) -> Iterator[_Interval]:
        return iter(list(self._intervals))

    def __len__(self) -> int:
        return len(self._intervals)
