"""Minimal typed event emitter + Deferred (reference common-utils
TypedEventEmitter, Deferred)."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class TypedEventEmitter:
    def __init__(self):
        self._listeners: Dict[str, List[Callable]] = {}

    def on(self, event: str, fn: Callable) -> Callable:
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def once(self, event: str, fn: Callable) -> None:
        def wrapper(*args, **kwargs):
            self.off(event, wrapper)
            fn(*args, **kwargs)
        self.on(event, wrapper)

    def off(self, event: str, fn: Callable) -> None:
        listeners = self._listeners.get(event)
        if listeners and fn in listeners:
            listeners.remove(fn)

    def emit(self, event: str, *args, **kwargs) -> None:
        for fn in list(self._listeners.get(event, [])):
            fn(*args, **kwargs)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, []))


class Deferred:
    """A one-shot promise usable across threads (reference common-utils
    Deferred): resolve/reject once; result() blocks until settled. Over
    in-process drivers settlement is usually synchronous, so result()
    returns immediately; over network drivers the resolver runs on the
    connection's reader thread."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    @property
    def settled(self) -> bool:
        return self._event.is_set()

    def resolve(self, value: Any = None) -> None:
        if not self._event.is_set():
            self._value = value
            self._event.set()

    def reject(self, error: BaseException) -> None:
        if not self._event.is_set():
            self._error = error
            self._event.set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("deferred not settled within timeout")
        if self._error is not None:
            raise self._error
        return self._value
