"""Minimal typed event emitter (reference common-utils TypedEventEmitter)."""

from __future__ import annotations

from typing import Callable, Dict, List


class TypedEventEmitter:
    def __init__(self):
        self._listeners: Dict[str, List[Callable]] = {}

    def on(self, event: str, fn: Callable) -> Callable:
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def once(self, event: str, fn: Callable) -> None:
        def wrapper(*args, **kwargs):
            self.off(event, wrapper)
            fn(*args, **kwargs)
        self.on(event, wrapper)

    def off(self, event: str, fn: Callable) -> None:
        listeners = self._listeners.get(event)
        if listeners and fn in listeners:
            listeners.remove(fn)

    def emit(self, event: str, *args, **kwargs) -> None:
        for fn in list(self._listeners.get(event, [])):
            fn(*args, **kwargs)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, []))
