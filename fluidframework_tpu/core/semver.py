"""Semver-range helpers shared by the code loader (client) and package
registry (server) — reference: both web-code-loader and auspkn resolve
npm-style version ranges."""

from __future__ import annotations

from typing import Tuple


def parse_version(version: str) -> Tuple[int, ...]:
    return tuple(int(p) for p in version.split("."))


def satisfies(version: str, spec: str) -> bool:
    """Minimal semver-range check: exact, "^x.y.z" (same major, >=),
    "~x.y.z" (same major.minor, >=), "*" / "latest" (any)."""
    if spec in ("*", "latest", "", None):
        return True
    v = parse_version(version)
    if spec.startswith("^"):
        base = parse_version(spec[1:])
        return v[0] == base[0] and v >= base
    if spec.startswith("~"):
        base = parse_version(spec[1:])
        return v[:2] == base[:2] and v >= base
    return v == parse_version(spec)
