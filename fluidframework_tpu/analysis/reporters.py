"""Reporters: human-readable listing and machine-readable JSON.

Both end with the same one-line JSON summary
(``{"violations": N, "baselined": M}``) so `make lint-analysis` output
can be trend-tracked by the bench tooling with a tail -1.
"""

from __future__ import annotations

import json
from typing import IO

from .engine import AnalysisResult
from .registry import RULES


def render_human(result: AnalysisResult, stream: IO[str],
                 show_baselined: bool = False) -> None:
    for v in result.violations:
        stream.write(v.render() + "\n")
        if v.line_text:
            stream.write(f"    {v.line_text}\n")
        rule = RULES.get(v.rule_id)
        if rule is not None:
            stream.write(f"    hint: {rule.rationale}\n")
    if show_baselined:
        for v in result.baselined:
            stream.write(f"baselined: {v.render()}\n")
    if result.violations:
        stream.write(
            f"\n{len(result.violations)} new violation(s) across "
            f"{result.files} file(s) "
            f"({len(result.baselined)} baselined, "
            f"{result.suppressed} suppressed inline).\n"
            f"Fix them, add `# fluidlint: disable=RULE — reason`, or "
            f"accept with --write-baseline (and justify in the entry).\n")
    stream.write(json.dumps(result.summary) + "\n")


def render_json(result: AnalysisResult, stream: IO[str]) -> None:
    payload = {
        "files": result.files,
        "suppressed": result.suppressed,
        # Analyzer perf trend: wall time + cache effectiveness ride
        # every JSON report so an incremental (cached) run's speedup is
        # verifiable from the report alone.
        "wall_ms": round(result.wall_ms, 3),
        "race_rules_wall_ms": round(result.race_rules_wall_ms, 3),
        "placement_rules_wall_ms":
            round(result.placement_rules_wall_ms, 3),
        "cache": {"hits": result.cache_hits,
                  "misses": result.cache_misses},
        "summary": result.summary,
        "violations": [
            {"rule": v.rule_id, "path": v.path, "line": v.line,
             "col": v.col, "symbol": v.symbol, "message": v.message,
             "fingerprint": v.fingerprint}
            for v in result.violations],
        "baselined": [
            {"rule": v.rule_id, "path": v.path, "line": v.line,
             "fingerprint": v.fingerprint}
            for v in result.baselined],
    }
    stream.write(json.dumps(payload, indent=2) + "\n")
    stream.write(json.dumps(result.summary) + "\n")
