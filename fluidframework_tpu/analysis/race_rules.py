"""RC* rules: whole-program lockset race detection (fluidlint v3).

Four rule families over the concurrency model
(concurrency_model.py), guarding the thread/lock discipline of the
server tier the way lifecycle_rules.py guards the donated-buffer
discipline. The model discovers thread roots (spawned threads, executor
hand-offs, HTTP handler entry points, pump subscribe callbacks) and
lock objects, computes per-function held-lockset summaries with
transitive inheritance across the call graph, and intersects locksets
per shared instance attribute:

* ``SHARED_STATE_NO_LOCK`` — a cross-thread attribute whose lockset
  intersection over all accesses is empty (the Eraser condition);
* ``ATOMICITY_CHECK_THEN_ACT`` — read-test-write of the same shared
  attribute where the guarding lock is released between test and act
  (two distinct acquisitions of the same lock);
* ``LOCK_ORDER_INVERSION`` — two locks acquired in both orders on
  different paths, the classic deadlock shape (fires only when BOTH
  orders exist);
* ``SIGNAL_WITHOUT_LOCK`` — ``Condition.notify``/``wait`` outside the
  condition's owning lock.

Deliberate lock-free patterns are annotated, not silently tolerated:
``# fluidlint: guarded-by=<attr>`` asserts a lock the model cannot see
(verified at runtime by ``testing/lockcheck.py``), and
``# fluidlint: disable=RULE — reason`` documents the monotonic-read
patterns that are racy-by-design.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from .engine import ModuleContext, Violation
from .registry import rule

#: Rule ids of this family — the engine uses this set to let race
#: findings participate in --changed-only reach expansion.
RACE_RULE_IDS = frozenset({
    "SHARED_STATE_NO_LOCK", "ATOMICITY_CHECK_THEN_ACT",
    "LOCK_ORDER_INVERSION", "SIGNAL_WITHOUT_LOCK",
})


def _model_for(ctx: ModuleContext):
    """The whole-program concurrency model: analyze_paths attaches a
    ProgramContext spanning every analyzed module; analyze_source
    (fixtures) builds a single-module one on demand."""
    from .lifecycle_rules import _program_for
    return _program_for(ctx).concurrency()


def _emit(ctx: ModuleContext, rule_id: str) -> Iterator[Violation]:
    from .concurrency_model import in_scope
    if not in_scope(ctx.path):
        return
    model = _model_for(ctx)
    seen: Set[tuple] = set()
    for f in model.findings_for(ctx.path):
        if f.rule_id != rule_id:
            continue
        key = (getattr(f.node, "lineno", 0),
               getattr(f.node, "col_offset", 0), f.message)
        if key in seen:
            continue
        seen.add(key)
        yield _violation(ctx, f)


def _violation(ctx: ModuleContext, finding) -> Violation:
    node = finding.node
    # Model nodes come from this module's own tree, so the engine's
    # symbol/line machinery applies directly; synthetic nodes (lambda
    # wrappers) still carry copied locations.
    if not isinstance(node, ast.AST):  # pragma: no cover - defensive
        node = ast.Pass()
        node.lineno, node.col_offset = 1, 0
    return ctx.violation(finding.rule_id, node, finding.message)


@rule("SHARED_STATE_NO_LOCK",
      "Cross-thread attribute with an empty lockset intersection over "
      "its accesses",
      family="race",
      rationale="An attribute written from one thread root and touched "
                "from another with no common lock is a data race: torn "
                "dict/list mutation, lost counter updates, or "
                "RuntimeError('dict changed size during iteration') on "
                "the monitor's probe threads. Guard every access with "
                "one lock, or annotate the deliberate pattern "
                "(# fluidlint: guarded-by=<attr> for a lock the model "
                "cannot see, disable= for monotonic stat reads).")
def shared_state_no_lock(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "SHARED_STATE_NO_LOCK")


@rule("ATOMICITY_CHECK_THEN_ACT",
      "Read-test-write of a shared attribute with the lock released "
      "between test and act",
      family="race",
      rationale="Holding the lock for the test and re-acquiring it for "
                "the act publishes a stale decision: another thread can "
                "invalidate the test in the gap (pop from an emptied "
                "queue, double-free a lane). Widen one critical section "
                "over the whole check-then-act.")
def atomicity_check_then_act(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "ATOMICITY_CHECK_THEN_ACT")


@rule("LOCK_ORDER_INVERSION",
      "Two locks acquired in both orders on different paths",
      family="race",
      rationale="Thread A takes lock1 then lock2; thread B takes lock2 "
                "then lock1 — each holds what the other needs and the "
                "server deadlocks under exactly the load that makes "
                "both paths hot. Fires only when BOTH orders exist; "
                "pick one global order (document it on the lock decl).")
def lock_order_inversion(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "LOCK_ORDER_INVERSION")


@rule("SIGNAL_WITHOUT_LOCK",
      "Condition.notify/wait outside its owning lock",
      family="race",
      rationale="notify()/wait() on a threading.Condition whose lock "
                "is not held raises RuntimeError at runtime — or, for "
                "the test-then-wait idiom, misses the wakeup entirely "
                "and hangs the waiter. Wrap the call in `with cond:`.")
def signal_without_lock(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "SIGNAL_WITHOUT_LOCK")
