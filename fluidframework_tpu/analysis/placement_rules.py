"""PL* rules: whole-program placement & sharding dataflow (fluidlint v4).

Five rule families over the placement model (placement_model.py),
guarding the mesh discipline of the mergetree/server/parallel tiers the
way race_rules.py guards the thread/lock discipline. The model tracks
every binding through the placement lattice (host < replicated <
mesh-sharded(PartitionSpec) < donated-gone), indexing mesh
construction, spec literals, ``device_put``/``with_sharding_constraint``
transfers, the house placement helpers, and jit dispatch boundaries
(``donate_argnums``/``in_shardings``):

* ``MESH_DONATION_GATE`` — a donated argument that is definitely
  mesh-sharded (the R6 warm-reload corruption, pinned by the seeded
  fixture from the test_mesh_serving repro);
* ``UNSPECCED_POOL`` — a lane/page-pool pytree reaching a mesh dispatch
  with no matching partition rule (silently replicated onto every
  device);
* ``PSPEC_MISMATCH`` — spec axis names absent from every mesh the
  program builds, or spec arity exceeding the target's known rank;
* ``HOST_READ_OF_SHARDED`` — ``.item()``/``int()``/``np.asarray`` on a
  mesh-sharded binding outside the sanctioned gather helpers;
* ``SHARD_AXIS_DRIFT`` — one pytree placed or dispatched under two
  different specs with no explicit reshard.

Rules fire on DEFINITE placements only (straight-line code); the
conditional single-chip/mesh dual-mode paths stay quiet and are covered
dynamically by ``testing/shardcheck.py`` against the same rule table
(``mergetree/partition_rules.py``) — static prediction and runtime
``.sharding`` cannot silently drift apart.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from .engine import ModuleContext, Violation
from .registry import rule

#: Rule ids of this family — the engine uses this set to let placement
#: findings participate in --changed-only reach expansion.
PLACEMENT_RULE_IDS = frozenset({
    "MESH_DONATION_GATE", "UNSPECCED_POOL", "PSPEC_MISMATCH",
    "HOST_READ_OF_SHARDED", "SHARD_AXIS_DRIFT",
})


def _model_for(ctx: ModuleContext):
    """The whole-program placement model: analyze_paths attaches a
    ProgramContext spanning every analyzed module; analyze_source
    (fixtures) builds a single-module one on demand."""
    from .lifecycle_rules import _program_for
    return _program_for(ctx).placement()


def _emit(ctx: ModuleContext, rule_id: str) -> Iterator[Violation]:
    from .placement_model import in_scope
    if not in_scope(ctx.path):
        return
    model = _model_for(ctx)
    seen: Set[tuple] = set()
    for f in model.findings_for(ctx.path):
        if f.rule_id != rule_id:
            continue
        key = (getattr(f.node, "lineno", 0),
               getattr(f.node, "col_offset", 0), f.message)
        if key in seen:
            continue
        seen.add(key)
        yield _violation(ctx, f)


def _violation(ctx: ModuleContext, finding) -> Violation:
    node = finding.node
    if not isinstance(node, ast.AST):  # pragma: no cover - defensive
        node = ast.Pass()
        node.lineno, node.col_offset = 1, 0
    return ctx.violation(finding.rule_id, node, finding.message)


@rule("MESH_DONATION_GATE",
      "Mesh-sharded buffer donated across a jit dispatch boundary",
      family="placement",
      rationale="Donating a dp-sharded plane corrupts it on warm reload "
                "through the persistent compile cache (R6, "
                "docs/serving_pipeline.md): the reloaded executable "
                "aliases the donated buffer before the restore path "
                "re-places it. Every paged pool entry point carries a "
                "non-donating keep twin selected at construction "
                "(mergetree/paging.py) — dispatch through it on meshes, "
                "never the donating form.")
def mesh_donation_gate(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "MESH_DONATION_GATE")


@rule("UNSPECCED_POOL",
      "Lane/page-pool pytree reaching a mesh dispatch with no matching "
      "partition rule",
      family="placement",
      rationale="A pool that never went through "
                "match_partition_rules/place_with_rules "
                "(mergetree/partition_rules.py) gets replicated onto "
                "every device at the first mesh dispatch: page capacity "
                "stops scaling with the mesh and the replication "
                "transfer lands on the serving path. Place the pool "
                "under the rule table before dispatching it.")
def unspecced_pool(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "UNSPECCED_POOL")


@rule("PSPEC_MISMATCH",
      "PartitionSpec naming an axis no mesh has, or exceeding the "
      "target's rank",
      family="placement",
      rationale="A spec axis absent from the mesh (or more spec entries "
                "than the array has dimensions) raises inside jax at "
                "dispatch time — but only on the first mesh-shaped run, "
                "which for dual-mode code means in production, not in "
                "single-chip CI. The model checks every literal spec "
                "against the union of axes any mesh in the program "
                "declares.")
def pspec_mismatch(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "PSPEC_MISMATCH")


@rule("HOST_READ_OF_SHARDED",
      "Scalar/host read of a mesh-sharded binding outside the gather "
      "helpers",
      family="placement",
      rationale=".item()/int()/np.asarray on a mesh-sharded array "
                "gathers every shard through a blocking device-to-host "
                "transfer — a serving-path stall that grows with the "
                "mesh. Route host reads through a sanctioned gather "
                "helper (*gather*/*to_host*/*fetch* functions), or keep "
                "the reduction on-device and read the replicated "
                "scalar.")
def host_read_of_sharded(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "HOST_READ_OF_SHARDED")


@rule("SHARD_AXIS_DRIFT",
      "One pytree placed or dispatched under two different specs with "
      "no explicit reshard",
      family="placement",
      rationale="Two consumers pinning the same buffer to different "
                "specs makes GSPMD insert a full cross-device reshard "
                "on every call — silent all-to-all traffic that "
                "profiles as 'mysterious collective'. Rebind through an "
                "explicit reshard (`x = device_put(x, ...)`) or unify "
                "the consumers on one spec in the rule table.")
def shard_axis_drift(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "SHARD_AXIS_DRIFT")
