"""Intraprocedural dataflow for the donated-buffer lifecycle rules.

The donated-dispatch discipline (docs/serving_pipeline.md R6,
docs/paged_memory.md) says: once a buffer is handed to a
``donate_argnums`` call, every binding that aliases it is dead until
reassigned. fluidlint v1 could not see that — it pattern-matched names
inside single functions. This pass walks each HOST function (jitted
bodies are traced code: donation applies at their call boundary, not
inside the trace) with a small abstract interpreter:

* **regions** — every binding (local name or ``self.x.y`` attribute
  chain) maps to an abstract buffer region; ``a = b`` aliases, tuple
  unpacks of a composite share its region (pytree-carry leaves die with
  the carry), ``tuple(xs)``/``list(xs)`` share ``xs``'s region, a fresh
  call result gets a fresh region, reassignment kills.
* **donation** — at a call site whose callee resolves (ProgramIndex)
  to a donating signature, the regions read by the donated argument
  expressions are marked donated; same-statement assignment targets
  rebind AFTER the marking, so the canonical
  ``state, ys = step(state, xs)`` stays clean.
* **reads** — a later Load of a donated region is USE_AFTER_DONATE; a
  ``self.*`` store left holding a donated region at function exit (or
  a store of an already-donated value) is DONATED_ESCAPE.
* **dtype lattice** — int dtypes (int16/int32/int64/uint32/unknown)
  propagate through ``astype``/``asarray``/arithmetic/subscripts so
  PAGE_ID_DTYPE v2 follows a page-id through intermediate bindings the
  old regex never saw.

Sanctioned patterns are modeled, not suppressed: metadata probes
(``.shape``/``.dtype``/``.is_deleted()``, ``jax.tree_util.tree_leaves``
— the burst fallback's liveness-probe-then-reraise), calls whose
resolved callee only reads a parameter's metadata (``_gone``; including
through ``map(probe, xs)``), and the non-donating ``*_keep`` variants
whose signatures simply donate less.

Branches merge conservatively (donated-anywhere stays donated; a kill
on one branch does not kill the merge), ``except`` handlers see every
donation the ``try`` body performed WITHOUT its rebinds (the handler
runs at an arbitrary raise point — exactly the PR 7 burst-fallback
hazard), and loop bodies are processed once (no fixpoint).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import ProgramIndex, ResolvedCallee
from .engine import _dotted

# Attribute reads that touch metadata, never buffer contents.
METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "sharding", "device",
    "devices", "is_deleted", "is_fully_replicated", "capacity", "aval",
    "weak_type",
}

# Calls whose reading of an argument is a metadata/structure probe.
METADATA_CALLS = {
    "len", "isinstance", "type", "id", "repr", "hasattr", "getattr",
    "tree_leaves", "tree_structure", "jax.tree_util.tree_leaves",
    "jax.tree_util.tree_structure", "tree_util.tree_leaves",
    "tree_util.tree_structure", "tree_flatten",
    "jax.tree_util.tree_flatten", "tree_util.tree_flatten",
}

_MAX_CHAIN_DEPTH = 4

# -- dtype lattice -----------------------------------------------------------

_INT_WIDTH = {"int8": 8, "int16": 16, "int32": 32, "int64": 64,
              "uint8": 8, "uint16": 16, "uint32": 32, "uint64": 64}

#: Integer dtypes that are NOT the canonical int32 page index: narrower
#: wraps past 32k pages, wider doubles transfers, unsigned 32-bit
#: destroys the -1 padding sentinel.
BAD_PAGE_DTYPES = {"int8", "int16", "int64",
                   "uint8", "uint16", "uint32", "uint64"}

_DTYPE_FACTORIES = {
    "asarray", "array", "zeros", "ones", "empty", "full", "arange",
    "full_like", "zeros_like", "ones_like",
}

_NUMPY_MODULES = ("jnp", "np", "numpy", "jax.numpy")


def dtype_literal(node: ast.AST) -> Optional[str]:
    """'int64' for ``np.int64``/``jnp.int64``/``"int64"`` nodes."""
    if isinstance(node, ast.Attribute) and \
            node.attr in _INT_WIDTH and \
            _dotted(node.value) in _NUMPY_MODULES:
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _INT_WIDTH:
        return node.value
    return None


def join_dtypes(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Arithmetic promotion, pessimistically: unsigned taint sticks
    (it is the sentinel-destroying case), otherwise the wider wins;
    one-sided knowledge propagates."""
    if a is None:
        return b
    if b is None:
        return a
    if a.startswith("u") or b.startswith("u"):
        return a if a.startswith("u") else b
    return a if _INT_WIDTH.get(a, 0) >= _INT_WIDTH.get(b, 0) else b


# -- the abstract state ------------------------------------------------------


@dataclass(frozen=True)
class DonationSite:
    line: int
    callee: str
    binding: str  # the binding whose region was donated (for messages)


class Env:
    """Binding -> region + per-region facts. Copy-on-branch."""

    __slots__ = ("vars", "donated", "dtype", "page", "stores",
                 "terminated")

    def __init__(self):
        self.vars: Dict[str, int] = {}
        self.donated: Dict[int, DonationSite] = {}
        self.dtype: Dict[int, str] = {}
        self.page: Set[int] = set()
        # self.* attr chains stored in THIS function: chain -> store line
        self.stores: Dict[str, int] = {}
        self.terminated: Optional[str] = None  # "return" | "raise" | loop

    def copy(self) -> "Env":
        out = Env()
        out.vars = dict(self.vars)
        out.donated = dict(self.donated)
        out.dtype = dict(self.dtype)
        out.page = set(self.page)
        out.stores = dict(self.stores)
        out.terminated = self.terminated
        return out


@dataclass
class FunctionSummary:
    """Interprocedural facts about one function, keyed by qualname."""
    qualname: str
    donated_params: Set[str] = field(default_factory=set)
    donated_positions: Set[int] = field(default_factory=set)
    metadata_only_params: Set[int] = field(default_factory=set)


@dataclass
class Finding:
    kind: str          # "USE_AFTER_DONATE" | "DONATED_ESCAPE" | "PAGE_ID_DTYPE"
    node: ast.AST
    message: str


class FunctionDataflow(ast.NodeVisitor):
    """One pass over one function body. Drives both the donation
    lifecycle findings and the page-id dtype lattice."""

    def __init__(self, fn: ast.AST, module: str,
                 class_name: Optional[str],
                 index: Optional[ProgramIndex],
                 summaries: Optional[Dict[str, "FunctionSummary"]] = None,
                 page_name_re=None,
                 paged_kernel_names: Optional[Set[str]] = None,
                 track_donation: bool = True):
        self.fn = fn
        self.module = module
        self.class_name = class_name
        self.index = index
        self.summaries = summaries or {}
        self.page_name_re = page_name_re
        self.paged_kernel_names = paged_kernel_names or set()
        self.track_donation = track_donation
        self.findings: List[Finding] = []
        self._next_region = 0
        self._seen_nodes: Set[int] = set()
        self._escaped: Set[Tuple[str, int]] = set()
        self.local_defs: Dict[str, ast.AST] = {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn}
        # Nested defs get metadata-only summaries of their own so the
        # `_gone(self.tstate)` liveness-probe closure stays sanctioned.
        self.local_summaries: Dict[str, FunctionSummary] = {}
        from .callgraph import FunctionDecl
        for name, node in self.local_defs.items():
            qual = f"{module}:<local>.{name}"
            decl = FunctionDecl(qualname=qual, module=module, name=name,
                                class_name=class_name, node=node)
            s = FunctionSummary(qual)
            s.metadata_only_params = _metadata_only_params(decl)
            self.local_summaries[qual] = s
        self.exit_envs: List[Env] = []

    def _summary_for(self, qualname: str) -> Optional["FunctionSummary"]:
        return self.summaries.get(qualname) or \
            self.local_summaries.get(qualname)

    # -- plumbing ----------------------------------------------------------
    def fresh(self) -> int:
        self._next_region += 1
        return self._next_region

    def region_of(self, env: Env, key: str, create: bool = True
                  ) -> Optional[int]:
        r = env.vars.get(key)
        if r is None and create:
            r = self.fresh()
            env.vars[key] = r
            if self.page_name_re is not None and \
                    self.page_name_re.search(key.rsplit(".", 1)[-1]):
                env.page.add(r)
        return r

    def bind(self, env: Env, key: str, region: int) -> None:
        env.vars[key] = region
        # Rebinding a root kills the chains hanging off it.
        prefix = key + "."
        for k in [k for k in env.vars if k.startswith(prefix)]:
            del env.vars[k]
        if self.page_name_re is not None and \
                self.page_name_re.search(key.rsplit(".", 1)[-1]):
            env.page.add(region)

    # -- analysis entry ----------------------------------------------------
    def run(self) -> List[Finding]:
        env = Env()
        args = self.fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            self.region_of(env, a.arg)
        # Pre-seed a region for every trackable chain the body mentions:
        # branch/handler env copies then agree on region ids, so a
        # donation inside a try body is visible to the except handler
        # even for chains (self.tstate) first touched inside the try.
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.Name, ast.Attribute)):
                chain = self._chain(node)
                if chain is not None:
                    self.region_of(env, chain)
        out = self._exec_block(self.fn.body, env)
        if out.terminated is None:
            self.exit_envs.append(out)
        self._check_escapes()
        return self.findings

    # -- statement execution ----------------------------------------------
    def _exec_block(self, stmts: List[ast.stmt], env: Env) -> Env:
        for stmt in stmts:
            if env.terminated:
                break
            env = self._exec(stmt, env)
        return env

    def _exec(self, stmt: ast.stmt, env: Env) -> Env:
        method = getattr(self, "_exec_" + type(stmt).__name__, None)
        if method is not None:
            return method(stmt, env)
        # Default: check reads in every expression the statement holds,
        # apply call effects, no binding changes.
        for expr in ast.iter_child_nodes(stmt):
            if isinstance(expr, ast.expr):
                self._read_check(expr, env)
                self._apply_call_effects(expr, env)
        return env

    # assignments ----------------------------------------------------------
    def _exec_Assign(self, stmt: ast.Assign, env: Env) -> Env:
        escape = self._escape_target(stmt) \
            if self._chain(stmt.value) is not None else None
        self._read_check(stmt.value, env, escape_store=escape)
        self._apply_call_effects(stmt.value, env)
        self._bind_targets(stmt.targets, stmt.value, env, stmt)
        return env

    def _exec_AnnAssign(self, stmt: ast.AnnAssign, env: Env) -> Env:
        if stmt.value is not None:
            escape = self._escape_target(stmt) \
                if self._chain(stmt.value) is not None else None
            self._read_check(stmt.value, env, escape_store=escape)
            self._apply_call_effects(stmt.value, env)
            self._bind_targets([stmt.target], stmt.value, env, stmt)
        return env

    def _exec_AugAssign(self, stmt: ast.AugAssign, env: Env) -> Env:
        self._read_check(stmt.value, env)
        target_key = self._chain(stmt.target)
        if target_key is not None:
            r = self.region_of(env, target_key)
            if r in env.donated:
                self._uad(stmt.target, target_key, env.donated[r], env)
            d = self._infer_dtype(stmt.value, env)
            if d is not None and r is not None:
                env.dtype[r] = join_dtypes(env.dtype.get(r), d)
                self._page_dtype_check(stmt.target, target_key, env, stmt)
        return env

    def _escape_target(self, stmt) -> Optional[str]:
        """When the statement is a plain ``self.x = <name-or-chain>``,
        a donated value read is an ESCAPE (stored into state that
        outlives the call), not a mere use."""
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        if len(targets) == 1:
            chain = self._chain(targets[0])
            if chain is not None and chain.startswith("self."):
                return chain
        return None

    def _bind_targets(self, targets, value: ast.expr, env: Env,
                      stmt: ast.stmt) -> None:
        for target in targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                if isinstance(value, (ast.Tuple, ast.List)) and \
                        len(value.elts) == len(target.elts):
                    for t, v in zip(target.elts, value.elts):
                        self._bind_targets([t], v, env, stmt)
                    continue
                src_key = self._chain(value)
                if src_key is not None:
                    # Unpacking a composite: the leaves share its
                    # region (donating the carry kills them all).
                    region = self.region_of(env, src_key)
                    for t in target.elts:
                        self._bind_simple(t, region, None, env, stmt)
                else:
                    # Call-result unpack: each leaf is its own fresh
                    # buffer — donating one later must not poison its
                    # siblings.
                    for t in target.elts:
                        self._bind_simple(t, self.fresh(), None, env,
                                          stmt)
                continue
            self._bind_value(target, value, env, stmt)

    def _bind_value(self, target: ast.expr, value: ast.expr, env: Env,
                    stmt: ast.stmt) -> None:
        key = self._chain(target)
        if key is None:
            return
        region, dtype = self._value_region(value, env)
        self._bind_simple(target, region, dtype, env, stmt)

    def _bind_simple(self, target: ast.expr, region: Optional[int],
                     dtype: Optional[str], env: Env,
                     stmt: ast.stmt) -> None:
        key = self._chain(target)
        if key is None:
            return
        if region is None:
            region = self.fresh()
        self.bind(env, key, region)
        if dtype is not None:
            env.dtype[region] = dtype
        if key.startswith("self."):
            env.stores[key] = getattr(stmt, "lineno", 0)
        self._page_dtype_check(target, key, env, stmt)

    def _value_region(self, value: ast.expr, env: Env
                      ) -> Tuple[Optional[int], Optional[str]]:
        """Abstract value of an expression: (region, dtype). Aliasing
        expressions return an EXISTING region; everything else is
        fresh."""
        dtype = self._infer_dtype(value, env)
        key = self._chain(value)
        if key is not None:
            return self.region_of(env, key), dtype
        if isinstance(value, ast.Subscript):
            base = self._chain(value.value)
            if base is not None:
                return self.region_of(env, base), dtype
            return self.fresh(), dtype
        if isinstance(value, ast.Call):
            fn = _dotted(value.func)
            if fn in ("tuple", "list") and len(value.args) == 1:
                inner = self._chain(value.args[0])
                if inner is not None:
                    return self.region_of(env, inner), dtype
            # jnp/np.asarray(x) with no dtype change can alias on JAX;
            # sharing the region keeps donation tracking sound there.
            if fn.rpartition(".")[2] == "asarray" and value.args and \
                    not value.keywords and len(value.args) == 1:
                inner = self._chain(value.args[0])
                if inner is not None:
                    return self.region_of(env, inner), dtype
            r = self.fresh()
            if self._page_taint_of(value, env):
                env.page.add(r)
            return r, dtype
        if isinstance(value, (ast.Tuple, ast.List)):
            r = self.fresh()
            return r, dtype
        if isinstance(value, (ast.BinOp, ast.UnaryOp)):
            r = self.fresh()
            if self._page_taint_of(value, env):
                env.page.add(r)
            return r, dtype
        if isinstance(value, ast.IfExp):
            r = self.fresh()
            return r, dtype
        return self.fresh(), dtype

    # expressions / other statements ---------------------------------------
    def _exec_Expr(self, stmt: ast.Expr, env: Env) -> Env:
        self._read_check(stmt.value, env)
        self._apply_call_effects(stmt.value, env)
        return env

    def _exec_Return(self, stmt: ast.Return, env: Env) -> Env:
        if stmt.value is not None:
            self._read_check(stmt.value, env)
            self._apply_call_effects(stmt.value, env)
        env.terminated = "return"
        self.exit_envs.append(env)
        return env

    def _exec_Raise(self, stmt: ast.Raise, env: Env) -> Env:
        if stmt.exc is not None:
            self._read_check(stmt.exc, env)
        env.terminated = "raise"
        return env

    def _exec_Delete(self, stmt: ast.Delete, env: Env) -> Env:
        for t in stmt.targets:
            key = self._chain(t)
            if key is not None:
                env.vars.pop(key, None)
        return env

    def _exec_Pass(self, stmt, env: Env) -> Env:
        return env

    def _exec_Continue(self, stmt, env: Env) -> Env:
        env.terminated = "continue"
        return env

    def _exec_Break(self, stmt, env: Env) -> Env:
        env.terminated = "break"
        return env

    def _exec_FunctionDef(self, stmt, env: Env) -> Env:
        return env  # nested defs analyzed as their own functions

    _exec_AsyncFunctionDef = _exec_FunctionDef

    def _exec_ClassDef(self, stmt, env: Env) -> Env:
        return env

    def _exec_Import(self, stmt, env: Env) -> Env:
        return env

    _exec_ImportFrom = _exec_Import
    _exec_Global = _exec_Import
    _exec_Nonlocal = _exec_Import
    _exec_Assert = None  # falls through to default (read check only)

    # control flow ---------------------------------------------------------
    def _exec_If(self, stmt: ast.If, env: Env) -> Env:
        self._read_check(stmt.test, env)
        self._apply_call_effects(stmt.test, env)
        env_t = self._exec_block(stmt.body, env.copy())
        env_f = self._exec_block(stmt.orelse, env.copy())
        return self._merge(env_t, env_f)

    def _exec_While(self, stmt: ast.While, env: Env) -> Env:
        self._read_check(stmt.test, env)
        body_env = self._exec_block(stmt.body, env.copy())
        if body_env.terminated in ("continue", "break"):
            body_env.terminated = None
        merged = self._merge(env.copy(), body_env)
        return self._exec_block(stmt.orelse, merged)

    def _exec_For(self, stmt: ast.For, env: Env) -> Env:
        self._read_check(stmt.iter, env)
        self._apply_call_effects(stmt.iter, env)
        loop_env = env.copy()
        self._bind_targets([stmt.target], ast.Constant(value=None),
                           loop_env, stmt)
        body_env = self._exec_block(stmt.body, loop_env)
        if body_env.terminated in ("continue", "break"):
            body_env.terminated = None
        merged = self._merge(env.copy(), body_env)
        return self._exec_block(stmt.orelse, merged)

    _exec_AsyncFor = _exec_For

    def _exec_With(self, stmt: ast.With, env: Env) -> Env:
        for item in stmt.items:
            self._read_check(item.context_expr, env)
            self._apply_call_effects(item.context_expr, env)
            if item.optional_vars is not None:
                self._bind_targets([item.optional_vars],
                                   item.context_expr, env, stmt)
        return self._exec_block(stmt.body, env)

    _exec_AsyncWith = _exec_With

    def _exec_Try(self, stmt: ast.Try, env: Env) -> Env:
        entry = env.copy()
        donations_before = dict(env.donated)
        body_env = self._exec_block(stmt.body, env)
        # The handler runs from an ARBITRARY raise point inside the try
        # body: it sees every donation the body performed, but none of
        # the rebinds that followed (the PR 7 burst-fallback shape —
        # after a failed donated dispatch, the carry is gone and the
        # assignment never happened).
        new_donations = {r: s for r, s in body_env.donated.items()
                         if r not in donations_before}
        handler_outs: List[Env] = []
        for handler in stmt.handlers:
            henv = entry.copy()
            henv.donated.update(new_donations)
            # Each donation records the binding it went through; rebind
            # that key to the donated region in the handler env so a
            # carry first PACKED inside the try body (absent from the
            # entry env, or rebound after the donation) still reads as
            # donated at the arbitrary raise point the handler models.
            for r, s in new_donations.items():
                henv.vars[s.binding] = r
            if handler.name:
                self.bind(henv, handler.name, self.fresh())
            hout = self._exec_block(handler.body, henv)
            handler_outs.append(hout)
        out = body_env
        for hout in handler_outs:
            out = self._merge(out, hout)
        out = self._exec_block(stmt.orelse, out)
        return self._exec_block(stmt.finalbody, out)

    _exec_TryStar = _exec_Try

    def _merge(self, a: Env, b: Env) -> Env:
        if a.terminated and not b.terminated:
            return b
        if b.terminated and not a.terminated:
            return a
        out = a.copy()
        for k, r in b.vars.items():
            if k not in out.vars:
                out.vars[k] = r
            elif out.vars[k] != r:
                # Conflicting bindings: a key that is donated ON ITS OWN
                # PATH stays donated in the merge (a kill on one branch
                # must not hide the hazard on the other), but a branch
                # that both donates AND rebinds (`if c: s = step(s, x)`)
                # leaves the other path untouched — there the donation
                # never happened, so the live region wins.
                a_donated = out.vars[k] in a.donated
                b_donated = r in b.donated
                if b_donated and not a_donated:
                    out.vars[k] = r
        out.donated.update(b.donated)
        for r, d in b.dtype.items():
            out.dtype[r] = join_dtypes(out.dtype.get(r), d)
        out.page |= b.page
        for k, line in b.stores.items():
            out.stores.setdefault(k, line)
        if a.terminated and b.terminated:
            out.terminated = a.terminated
        return out

    # -- donation effects --------------------------------------------------
    def _apply_call_effects(self, expr: ast.expr, env: Env) -> None:
        """Walk ``expr`` for calls that donate; mark the regions their
        donated argument expressions read."""
        if not self.track_donation:
            return
        for call in ast.walk(expr):
            if not isinstance(call, ast.Call):
                continue
            res = self._resolve(call)
            donation = None
            bound = False
            if res is not None:
                donation = res.donation
                bound = res.bound_self
                s = self._summary_for(res.qualname)
                if donation is None and s is not None:
                    if s.donated_positions or s.donated_params:
                        from .callgraph import DonationSignature
                        donation = DonationSignature(
                            callee=res.qualname.rsplit(":", 1)[-1],
                            positions=set(s.donated_positions),
                            names=set(s.donated_params))
            if donation is None:
                continue
            for arg in donation.donated_args(call, bound_self=bound):
                for key, node in self._donatable_keys(arg):
                    r = self.region_of(env, key)
                    if r is not None:
                        env.donated[r] = DonationSite(
                            line=getattr(call, "lineno", 0),
                            callee=donation.callee, binding=key)

    def _donatable_keys(self, arg: ast.expr
                        ) -> Iterable[Tuple[str, ast.AST]]:
        """Bindings whose buffers a donated argument expression hands
        over. Only COMPLETE trackable chains count: a ListComp or
        subscript-bearing expression is unmappable and stays untracked
        (conservative, quiet)."""
        key = self._chain(arg)
        if key is not None:
            yield key, arg
            return
        if isinstance(arg, (ast.Tuple, ast.List)):
            for el in arg.elts:
                yield from self._donatable_keys(el)
            return
        if isinstance(arg, ast.Call):
            fn = _dotted(arg.func)
            if fn in ("tuple", "list") and len(arg.args) == 1:
                yield from self._donatable_keys(arg.args[0])

    def _resolve(self, call: ast.Call) -> Optional[ResolvedCallee]:
        if self.index is None:
            return None
        return self.index.resolve_call(self.module, call,
                                       class_name=self.class_name,
                                       local_defs=self.local_defs)

    # -- read checking -----------------------------------------------------
    def _read_check(self, expr: ast.expr, env: Env,
                    escape_store: Optional[str] = None) -> None:
        """Flag Loads of donated regions inside ``expr`` (evaluated
        against the env BEFORE this statement's own donations/rebinds
        apply)."""
        if not self.track_donation or not env.donated:
            self._page_operand_check(expr, env)
            return
        self._scan_reads(expr, env, escape_store)
        self._page_operand_check(expr, env)

    def _scan_reads(self, node: ast.AST, env: Env,
                    escape_store: Optional[str],
                    parent_stack: Tuple[ast.AST, ...] = ()) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred execution: closures analyzed separately
        chain = self._chain(node) if isinstance(
            node, (ast.Name, ast.Attribute)) else None
        if chain is not None and isinstance(
                getattr(node, "ctx", ast.Load()), ast.Load):
            hit = self._donated_prefix(chain, env)
            if hit is not None:
                site, rest = hit
                # `state.shape` / `state.is_deleted` on a donated
                # `state` is a metadata probe; `state.sum()` (or any
                # other attribute) dereferences the buffer.
                if rest and rest[0] in METADATA_ATTRS:
                    pass
                elif not rest and self._is_metadata_read(node,
                                                         parent_stack):
                    pass
                elif escape_store is not None:
                    self._escaped.add((escape_store, site.line))
                    self.findings.append(Finding(
                        "DONATED_ESCAPE", node,
                        f"`{escape_store}` stores `{chain}`, whose "
                        f"buffer was donated to `{site.callee}` at line "
                        f"{site.line} (via `{site.binding}`); the store "
                        f"outlives the call and will read freed device "
                        f"memory"))
                else:
                    self._uad(node, chain, site, env)
            return  # chains checked whole, not per component
        for child in ast.iter_child_nodes(node):
            self._scan_reads(child, env, escape_store,
                             parent_stack + (node,))

    def _donated_prefix(self, chain: str, env: Env):
        """(DonationSite, remaining components) when the chain or any
        prefix of it maps to a donated region — reading `state.sum` is
        a read of donated `state`."""
        parts = chain.split(".")
        for cut in range(1, len(parts) + 1):
            prefix = ".".join(parts[:cut])
            r = env.vars.get(prefix)
            if r is not None and r in env.donated:
                return env.donated[r], parts[cut:]
        return None

    def _uad(self, node: ast.AST, chain: str, site: DonationSite,
             env: Env) -> None:
        key = id(node)
        if key in self._seen_nodes:
            return
        self._seen_nodes.add(key)
        self.findings.append(Finding(
            "USE_AFTER_DONATE", node,
            f"`{chain}` reads a buffer donated to `{site.callee}` at "
            f"line {site.line} (via `{site.binding}`) and not "
            f"reassigned since; the dispatch may already have reused "
            f"or freed it"))

    def _is_metadata_read(self, node: ast.AST,
                          parents: Tuple[ast.AST, ...]) -> bool:
        """Reads that only touch metadata (shape/dtype/liveness) are
        the sanctioned probe idiom — the burst fallback checks
        ``tree_leaves(x)[0].is_deleted()`` before deciding whether
        re-dispatch is safe, and that must stay quiet."""
        # Immediate attribute: x.shape, x.is_deleted, …
        for parent in reversed(parents):
            if isinstance(parent, ast.Attribute) and \
                    parent.attr in METADATA_ATTRS:
                return True
            if isinstance(parent, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in parent.ops):
                return True
            if isinstance(parent, ast.Call):
                fn = _dotted(parent.func)
                if fn in METADATA_CALLS or \
                        fn.rpartition(".")[2] in ("tree_leaves",
                                                  "tree_structure",
                                                  "tree_flatten"):
                    return True
                if fn in ("map", "filter") and parent.args and \
                        self._probe_fn(parent.args[0]):
                    return True
                res = self._resolve(parent)
                summ = self._summary_for(res.qualname) \
                    if res is not None else None
                if summ is not None:
                    # The read is an argument of a call whose resolved
                    # callee only probes that parameter's metadata.
                    try:
                        pos = parent.args.index(node)
                    except ValueError:
                        pos = next(
                            (i for i, a in enumerate(parent.args)
                             if node in ast.walk(a)), None)
                    if pos is not None and \
                            pos in summ.metadata_only_params:
                        return True
                return False  # a real call consumes the buffer
            if not isinstance(parent, (ast.Attribute, ast.Subscript,
                                       ast.Starred)):
                break
        return False

    def _probe_fn(self, expr: ast.AST) -> bool:
        """True when ``expr`` names a function whose param 0 is
        metadata-only (``map(_gone, states)``)."""
        if not isinstance(expr, ast.Name):
            return False
        fake = ast.Call(func=ast.Name(id=expr.id, ctx=ast.Load()),
                        args=[], keywords=[])
        ast.copy_location(fake, expr)
        res = self.index.resolve_call(
            self.module, fake, class_name=self.class_name,
            local_defs=self.local_defs) if self.index else None
        summ = self._summary_for(res.qualname) if res is not None else None
        return summ is not None and 0 in summ.metadata_only_params

    # -- escapes -----------------------------------------------------------
    def _check_escapes(self) -> None:
        """A ``self.*`` chain stored in this function and left holding
        a donated region on any clean exit path escapes the donation:
        instance state now points at freed device memory (the PR 5
        stale-lane-plane shape)."""
        reported: Set[Tuple[str, int]] = set(self._escaped)
        for env in self.exit_envs:
            for chain, line in env.stores.items():
                r = env.vars.get(chain)
                if r is None or r not in env.donated:
                    continue
                site = env.donated[r]
                if (chain, site.line) in reported:
                    continue
                reported.add((chain, site.line))
                node = ast.Pass()
                node.lineno = line or site.line
                node.col_offset = 0
                self.findings.append(Finding(
                    "DONATED_ESCAPE", node,
                    f"`{chain}` still holds the buffer donated to "
                    f"`{site.callee}` at line {site.line} (stored at "
                    f"line {line}) when the function returns; the "
                    f"stored plane outlives the dispatch as freed "
                    f"device memory"))

    # -- page-id dtype lattice ---------------------------------------------
    def _infer_dtype(self, expr: ast.expr, env: Env) -> Optional[str]:
        lit = dtype_literal(expr)
        if lit is not None:
            return lit
        key = self._chain(expr)
        if key is not None:
            r = env.vars.get(key)
            return env.dtype.get(r) if r is not None else None
        if isinstance(expr, ast.Subscript):
            base = self._chain(expr.value)
            if base is not None:
                r = env.vars.get(base)
                return env.dtype.get(r) if r is not None else None
            return None
        if isinstance(expr, ast.Call):
            return self._infer_call_dtype(expr, env)
        if isinstance(expr, ast.BinOp):
            return join_dtypes(self._infer_dtype(expr.left, env),
                               self._infer_dtype(expr.right, env))
        if isinstance(expr, ast.UnaryOp):
            return self._infer_dtype(expr.operand, env)
        if isinstance(expr, ast.IfExp):
            return join_dtypes(self._infer_dtype(expr.body, env),
                               self._infer_dtype(expr.orelse, env))
        return None

    def _infer_call_dtype(self, call: ast.Call, env: Env
                          ) -> Optional[str]:
        fn = _dotted(call.func)
        tail = fn.rpartition(".")[2]
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "astype":
            for arg in list(call.args) + [k.value for k in call.keywords]:
                lit = dtype_literal(arg)
                if lit is not None:
                    return lit
            return None
        if tail in _DTYPE_FACTORIES:
            for arg in list(call.args) + [k.value for k in call.keywords]:
                lit = dtype_literal(arg)
                if lit is not None:
                    return lit
            # asarray/array with no dtype: passes the input through.
            if tail in ("asarray", "array") and call.args:
                return self._infer_dtype(call.args[0], env)
            return None
        if tail in ("where", "minimum", "maximum"):
            dt = None
            for arg in call.args[-2:]:
                dt = join_dtypes(dt, self._infer_dtype(arg, env))
            return dt
        return None

    def _page_taint_of(self, expr: ast.expr, env: Env) -> bool:
        for sub in ast.walk(expr):
            key = self._chain(sub) if isinstance(
                sub, (ast.Name, ast.Attribute)) else None
            if key is None:
                continue
            if self.page_name_re is not None and \
                    self.page_name_re.search(key.rsplit(".", 1)[-1]):
                return True
            r = env.vars.get(key)
            if r is not None and r in env.page:
                return True
        return False

    def _page_dtype_check(self, target: ast.expr, key: str, env: Env,
                          stmt: ast.stmt) -> None:
        """Fire PAGE_ID_DTYPE when a page-named (or page-tainted)
        binding ends up with a non-int32 integer dtype."""
        if self.page_name_re is None:
            return
        r = env.vars.get(key)
        if r is None:
            return
        leaf = key.rsplit(".", 1)[-1]
        is_page = r in env.page or self.page_name_re.search(leaf)
        if not is_page:
            return
        env.page.add(r)
        d = env.dtype.get(r)
        if d is None or d not in BAD_PAGE_DTYPES:
            return
        node = self._dtype_node_in(stmt) or target
        self._emit_page(node, d, f"assigned to `{key}`")

    def _dtype_node_in(self, stmt: ast.stmt) -> Optional[ast.AST]:
        for sub in ast.walk(stmt):
            lit = dtype_literal(sub)
            if lit is not None and lit in BAD_PAGE_DTYPES:
                return sub
        return None

    def _emit_page(self, node: ast.AST, dtype: str, where: str) -> None:
        key = id(node)
        if key in self._seen_nodes:
            return
        self._seen_nodes.add(key)
        self.findings.append(Finding(
            "PAGE_ID_DTYPE", node,
            f"page-id dtype `{dtype}` {where} drifts from the "
            f"canonical int32 page-table index"))

    def _page_operand_check(self, expr: ast.expr, env: Env) -> None:
        """Operands of the gather/scatter-by-page-id kernel surface and
        ``.astype`` casts onto page-tainted values."""
        if self.page_name_re is None:
            return
        for call in ast.walk(expr):
            if not isinstance(call, ast.Call):
                continue
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "astype" and \
                    self._page_taint_of(call.func.value, env):
                for arg in list(call.args) + [k.value
                                              for k in call.keywords]:
                    lit = dtype_literal(arg)
                    if lit in BAD_PAGE_DTYPES:
                        base = self._chain(call.func.value) or "page id"
                        self._emit_page(arg, lit,
                                        f"cast onto `{base}`")
                continue
            fn = _dotted(call.func)
            tail = fn.rpartition(".")[2]
            if tail not in self.paged_kernel_names:
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                hit = False
                for sub in ast.walk(arg):
                    lit = dtype_literal(sub)
                    if lit in BAD_PAGE_DTYPES and not (
                            isinstance(sub, ast.AST) and
                            self._inside_astype_onto_page(sub, arg, env)):
                        self._emit_page(sub, lit,
                                        f"in a `{tail}` operand")
                        hit = True
                if hit:
                    continue
                # No syntactic cast: fall back to the lattice.
                key = self._chain(arg)
                if key is not None:
                    r = env.vars.get(key)
                    d = env.dtype.get(r) if r is not None else None
                    if d in BAD_PAGE_DTYPES:
                        self._emit_page(arg, d,
                                        f"in a `{tail}` operand")

    def _inside_astype_onto_page(self, node, arg, env) -> bool:
        """Avoid double-reporting a literal already flagged by the
        astype-onto-page check within the same operand."""
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "astype" and \
                    self._page_taint_of(sub.func.value, env) and \
                    any(s is node for s in ast.walk(sub)):
                return True
        return False

    # -- chains ------------------------------------------------------------
    def _chain(self, node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        if len(parts) > _MAX_CHAIN_DEPTH:
            return None
        return ".".join(reversed(parts))


# -- summaries ---------------------------------------------------------------


def compute_summaries(index: ProgramIndex,
                      iterations: int = 3
                      ) -> Dict[str, FunctionSummary]:
    """Per-function interprocedural facts, to a small fixpoint:

    * ``donated_params`` — params the function passes (as a bare name)
      to a donated position of a known donating callee, so plain
      wrappers propagate donation transitively;
    * ``metadata_only_params`` — params whose every read is a metadata
      probe (``_gone``-style liveness checks), safe to receive donated
      values.
    """
    summaries: Dict[str, FunctionSummary] = {}
    decls = list(index.iter_functions())
    for decl in decls:
        summaries[decl.qualname] = FunctionSummary(decl.qualname)
        summaries[decl.qualname].metadata_only_params = \
            _metadata_only_params(decl)
    for _ in range(iterations):
        changed = False
        for decl in decls:
            s = summaries[decl.qualname]
            donated = _direct_donated_params(decl, index, summaries)
            if donated - s.donated_params:
                s.donated_params |= donated
                params = decl.param_names
                s.donated_positions |= {
                    params.index(p) for p in donated if p in params}
                changed = True
        if not changed:
            break
    return summaries


def _direct_donated_params(decl, index: ProgramIndex,
                           summaries: Dict[str, FunctionSummary]
                           ) -> Set[str]:
    params = set(decl.param_names)
    if not params:
        return set()
    if decl.jit is not None:
        return set()  # jitted bodies: donation applies at their boundary
    out: Set[str] = set()
    local_defs = {n.name: n for n in ast.walk(decl.node)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                  and n is not decl.node}
    for call in ast.walk(decl.node):
        if not isinstance(call, ast.Call):
            continue
        res = index.resolve_call(decl.module, call,
                                 class_name=decl.class_name,
                                 local_defs=local_defs)
        if res is None:
            continue
        donation = res.donation
        if donation is None and res.qualname in summaries:
            s = summaries[res.qualname]
            if s.donated_positions or s.donated_params:
                from .callgraph import DonationSignature
                donation = DonationSignature(
                    callee=res.qualname, positions=set(s.donated_positions),
                    names=set(s.donated_params))
        if donation is None:
            continue
        for arg in donation.donated_args(call,
                                         bound_self=res.bound_self):
            if isinstance(arg, ast.Name) and arg.id in params:
                out.add(arg.id)
    return out


_METADATA_PARENT_OK = (ast.Attribute, ast.Subscript, ast.Compare)


_PROBE_MAX_NODES = 200


def _metadata_only_params(decl) -> Set[int]:
    """Param positions whose every Load in the body is a metadata
    probe. Parameters that are never read as data may safely receive a
    donated buffer. Only probe-sized functions qualify — a liveness
    probe is a handful of lines, and skipping the walk for real
    functions keeps the summary pass off the warm path's critical
    cost."""
    node = decl.node
    params = decl.param_names
    if not params:
        return set()
    parents: Dict[int, ast.AST] = {}
    count = 0
    for parent in ast.walk(node):
        count += 1
        if count > _PROBE_MAX_NODES:
            return set()
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent
    data_read: Set[str] = set()
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in params):
            continue
        cur = parents.get(id(sub))
        ok = False
        hops = 0
        probe = sub
        while cur is not None and hops < 6:
            if isinstance(cur, ast.Attribute) and \
                    cur.attr in METADATA_ATTRS:
                ok = True
                break
            if isinstance(cur, ast.Call):
                fn = _dotted(cur.func)
                if fn in METADATA_CALLS or \
                        fn.rpartition(".")[2] in ("tree_leaves",
                                                  "tree_structure",
                                                  "tree_flatten"):
                    ok = True
                break
            if isinstance(cur, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in cur.ops):
                ok = True
                break
            if not isinstance(cur, _METADATA_PARENT_OK):
                break
            probe = cur
            cur = parents.get(id(cur))
            hops += 1
        if not ok:
            data_read.add(sub.id)
    # A value DERIVED from a metadata call (leaves = tree_leaves(x);
    # leaves[0].is_deleted()) is probe plumbing: names assigned from
    # metadata calls whose own uses are all metadata reads are covered
    # by the loop above because the derived name is not a param.
    return {i for i, p in enumerate(params) if p not in data_read}
