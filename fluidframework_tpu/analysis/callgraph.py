"""Cross-module symbol/call graph for fluidlint's whole-program rules.

fluidlint v1 rules were single-module by design: a rule saw one
``ModuleContext`` and pattern-matched names. The donated-buffer
lifecycle rules (lifecycle_rules.py) need more — at a call site in
``tpu_sequencer.py`` they must know that ``serve_step.serve_burst``
donates its first three arguments, even though that fact lives in a
``functools.partial(jax.jit, donate_argnums=(0, 1, 2))(_serve_burst)``
assignment two modules away. This module builds that map once per run:

* every function/method def in the analyzed tree, keyed by qualname
  (``module:func`` / ``module:Class.method``);
* how each is jitted — decorator (``@jax.jit``, ``@partial(jax.jit,…)``),
  call form (``jax.jit(fn, …)``), or assignment-wrapper form
  (``name = functools.partial(jax.jit, …)(fn)`` and
  ``name = jax.jit(fn, …)``, including ``fn.__wrapped__`` targets);
* module import aliases (plain, from-import, relative) so a dotted
  callee resolves across the package;
* simple module-level aliases (``g = f``) and instance-attribute jit
  handles (``self._step = jax.jit(full_step, donate_argnums=(0, 1))``).

Resolution is intentionally name-based and conservative: an
unresolvable callee yields ``None`` and the dataflow pass simply models
no effect — whole-program soundness is traded for a near-zero false
positive rate, the same bargain every fluidlint rule makes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import (
    JitInfo,
    _dotted,
    _is_jit_ref,
    decorator_jit_info as _decorator_jit_info,
)

_MAX_ALIAS_HOPS = 8  # alias-chain bound: cycles and pathological chains stop


@dataclass
class FunctionDecl:
    """One function/method def somewhere in the analyzed tree."""
    qualname: str                  # "module:func" / "module:Class.meth"
    module: str                    # dotted module name
    name: str
    class_name: Optional[str]
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    jit: Optional[JitInfo] = None  # donation/static info when jitted

    @property
    def param_names(self) -> List[str]:
        args = self.node.args
        return [a.arg for a in args.posonlyargs + args.args]


@dataclass
class DonationSignature:
    """What a call site needs to know about a donating callee: which of
    ITS OWN argument positions/keywords hand their buffers over."""
    callee: str                       # display name for messages
    positions: Set[int] = field(default_factory=set)
    names: Set[str] = field(default_factory=set)

    def donated_args(self, call: ast.Call,
                     bound_self: bool = False) -> List[ast.AST]:
        """The argument expressions at donated positions of ``call``.
        ``bound_self`` shifts positions down by one (method called via
        ``self.m(...)``: param 0 is the bound instance). Starred args
        make positions unmappable — the call is skipped entirely, which
        is the conservative (quiet) choice."""
        if any(isinstance(a, ast.Starred) for a in call.args):
            return []
        shift = 1 if bound_self else 0
        out: List[ast.AST] = []
        for i, arg in enumerate(call.args):
            if (i + shift) in self.positions:
                out.append(arg)
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in self.names:
                out.append(kw.value)
        return out


@dataclass
class _JitWrap:
    """``name = jax.jit(target, …)`` / ``partial(jax.jit, …)(target)``
    at module level: ``name`` is a jitted callable over ``target``."""
    target: Optional[str]          # local name the wrap was applied to
    donate_argnums: Set[int]
    donate_argnames: Set[str]


class ModuleSymbols:
    """Per-module symbol table: defs, aliases, imports, jit wrappers."""

    def __init__(self, module: str, tree: ast.Module, path: str = ""):
        self.module = module
        self.path = path
        # A package __init__ is its own package for relative imports
        # (`from . import x` inside server/__init__.py resolves against
        # fluidframework_tpu.server, not its parent).
        self.is_package = path.replace("\\", "/").endswith("__init__.py")
        self.tree = tree
        self.functions: Dict[str, FunctionDecl] = {}
        self.methods: Dict[str, Dict[str, FunctionDecl]] = {}
        self.aliases: Dict[str, str] = {}          # name -> local name
        self.jit_wrappers: Dict[str, _JitWrap] = {}
        self.imports: Dict[str, str] = {}          # name -> absolute dotted
        # (class, attr) -> _JitWrap for `self.attr = jax.jit(fn, …)`
        self.attr_wrappers: Dict[Tuple[str, str], _JitWrap] = {}
        self._index()

    # -- indexing ----------------------------------------------------------
    def _index(self) -> None:
        # Imports index from the WHOLE tree: this codebase routinely
        # defers imports into function bodies (`from . import
        # serve_step` inside the dispatch path) and those aliases must
        # still resolve at call sites. Collisions are rare enough that
        # a module-wide alias table is the right trade.
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(node)
        for stmt in self.tree.body:
            self._index_stmt(stmt, class_name=None)

    def _index_stmt(self, stmt: ast.stmt, class_name: Optional[str]) -> None:
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            pass  # indexed tree-wide in _index
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            decl = FunctionDecl(
                qualname=(f"{self.module}:{class_name}.{stmt.name}"
                          if class_name else f"{self.module}:{stmt.name}"),
                module=self.module, name=stmt.name, class_name=class_name,
                node=stmt, jit=_decorator_jit_info(stmt))
            if class_name is None:
                self.functions[stmt.name] = decl
            else:
                self.methods.setdefault(class_name, {})[stmt.name] = decl
            if class_name is not None:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Assign):
                        self._index_attr_wrap(sub, class_name)
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                self._index_stmt(sub, class_name=stmt.name)
        elif isinstance(stmt, ast.Assign) and class_name is None:
            self._index_module_assign(stmt)
        elif isinstance(stmt, (ast.If, ast.Try)):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.stmt):
                    self._index_stmt(sub, class_name)

    def _index_import(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                self.imports[local] = target
        elif isinstance(stmt, ast.ImportFrom):
            base = self._resolve_from_base(stmt)
            if base is None:
                return
            for alias in stmt.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                self.imports[local] = (f"{base}.{alias.name}"
                                       if base else alias.name)

    def _resolve_from_base(self, stmt: ast.ImportFrom) -> Optional[str]:
        if stmt.level == 0:
            return stmt.module or ""
        # Relative: peel the module's own dotted name down to its
        # package, then climb one level per extra dot.
        parts = self.module.split(".")
        if not self.is_package:
            parts = parts[:-1]
        up = stmt.level - 1
        if up > len(parts):
            return None
        base_parts = parts[:len(parts) - up] if up else parts
        if stmt.module:
            base_parts = base_parts + stmt.module.split(".")
        return ".".join(base_parts)

    def _index_module_assign(self, stmt: ast.Assign) -> None:
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0],
                                                    ast.Name):
            return
        name = stmt.targets[0].id
        wrap = _parse_jit_wrap(stmt.value)
        if wrap is not None:
            self.jit_wrappers[name] = wrap
            return
        if isinstance(stmt.value, ast.Name):
            self.aliases[name] = stmt.value.id

    def _index_attr_wrap(self, stmt: ast.Assign, class_name: str) -> None:
        """``self.attr = jax.jit(fn, donate_argnums=…)`` inside a method:
        the instance attribute is a jitted callable other methods invoke
        as ``self.attr(…)`` (server/bridge.py's ``self._step``)."""
        if len(stmt.targets) != 1:
            return
        t = stmt.targets[0]
        if not (isinstance(t, ast.Attribute) and
                isinstance(t.value, ast.Name) and t.value.id == "self"):
            return
        wrap = _parse_jit_wrap(stmt.value)
        if wrap is not None:
            self.attr_wrappers[(class_name, t.attr)] = wrap


def _parse_jit_wrap(value: ast.AST) -> Optional[_JitWrap]:
    """Recognize the two assignment-wrapper jit forms:
    ``jax.jit(fn, donate_argnums=…)`` and
    ``functools.partial(jax.jit, donate_argnums=…)(fn)``; ``fn`` may be
    a Name or ``name.__wrapped__`` (unwrapping an already-jitted def)."""
    if not isinstance(value, ast.Call):
        return None
    donate_nums: Set[int] = set()
    donate_names: Set[str] = set()
    target_expr: Optional[ast.AST] = None
    if _is_jit_ref(value.func) and value.args:
        target_expr = value.args[0]
        _collect_donates(value.keywords, donate_nums, donate_names)
    elif (isinstance(value.func, ast.Call)
          and _dotted(value.func.func) in ("functools.partial", "partial")
          and value.func.args and _is_jit_ref(value.func.args[0])
          and value.args):
        target_expr = value.args[0]
        _collect_donates(value.func.keywords, donate_nums, donate_names)
    else:
        return None
    target = _wrap_target_name(target_expr)
    return _JitWrap(target=target, donate_argnums=donate_nums,
                    donate_argnames=donate_names)


def _wrap_target_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and expr.attr == "__wrapped__":
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _collect_donates(keywords, nums: Set[int], names: Set[str]) -> None:
    from .engine import _int_elems, _str_elems
    for kw in keywords:
        if kw.arg == "donate_argnums":
            nums |= _int_elems(kw.value)
        elif kw.arg == "donate_argnames":
            names |= _str_elems(kw.value)


@dataclass
class ResolvedCallee:
    """A call site resolved to a program symbol: the def (when found),
    its donation signature (when it donates), and whether the call binds
    ``self`` (method form — donated positions shift by one)."""
    qualname: str
    decl: Optional[FunctionDecl]
    donation: Optional[DonationSignature]
    bound_self: bool = False


class ProgramIndex:
    """The whole-program symbol/call graph.

    Build it from ``(module_name, tree, path)`` triples (the engine
    hands it every parsed ``ModuleContext``); query with
    :meth:`resolve_call` from a rule/dataflow visitor positioned inside
    one module, or :meth:`call_edges` for the plain caller→callee graph
    the unit tests exercise."""

    def __init__(self, modules: Sequence[Tuple[str, ast.Module, str]]):
        self.modules: Dict[str, ModuleSymbols] = {}
        for name, tree, path in modules:
            self.modules[name] = ModuleSymbols(name, tree, path)

    # -- symbol lookup -----------------------------------------------------
    def lookup(self, module: str, name: str,
               _hops: int = 0) -> Optional[ResolvedCallee]:
        """Resolve a bare name in ``module`` to a program symbol,
        chasing aliases, jit wrappers, and from-imports."""
        syms = self.modules.get(module)
        if syms is None or _hops > _MAX_ALIAS_HOPS:
            return None
        if name in syms.functions:
            decl = syms.functions[name]
            return ResolvedCallee(decl.qualname, decl,
                                  _decl_donation(decl))
        if name in syms.jit_wrappers:
            return self._resolve_wrap(syms, name, syms.jit_wrappers[name],
                                      _hops)
        if name in syms.aliases:
            return self.lookup(module, syms.aliases[name], _hops + 1)
        if name in syms.imports:
            return self._lookup_dotted(syms.imports[name], _hops + 1)
        return None

    def _resolve_wrap(self, syms: ModuleSymbols, name: str, wrap: _JitWrap,
                      _hops: int) -> ResolvedCallee:
        decl = None
        if wrap.target:
            inner = self.lookup(syms.module, wrap.target, _hops + 1)
            if inner is not None:
                decl = inner.decl
        donation = None
        if wrap.donate_argnums or wrap.donate_argnames:
            names = set(wrap.donate_argnames)
            if decl is not None:
                params = decl.param_names
                names |= {params[i] for i in wrap.donate_argnums
                          if i < len(params)}
            donation = DonationSignature(
                callee=name, positions=set(wrap.donate_argnums),
                names=names)
        qual = decl.qualname if decl else f"{syms.module}:{name}"
        return ResolvedCallee(f"{syms.module}:{name}" if decl is None
                              else qual, decl, donation)

    def _lookup_dotted(self, dotted: str,
                       _hops: int = 0) -> Optional[ResolvedCallee]:
        """Resolve an absolute dotted symbol ("pkg.mod.func" or
        "pkg.mod" + later attribute): longest module prefix wins."""
        if _hops > _MAX_ALIAS_HOPS:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                rest = parts[cut:]
                if len(rest) == 1:
                    return self.lookup(mod, rest[0], _hops + 1)
                if len(rest) == 2:  # Class.method
                    decl = self.modules[mod].methods.get(
                        rest[0], {}).get(rest[1])
                    if decl is not None:
                        return ResolvedCallee(decl.qualname, decl,
                                              _decl_donation(decl))
                return None
        return None

    # -- call-site resolution ---------------------------------------------
    def resolve_call(self, module: str, call: ast.Call,
                     class_name: Optional[str] = None,
                     local_defs: Optional[Dict[str, ast.AST]] = None
                     ) -> Optional[ResolvedCallee]:
        """Resolve ``call``'s callee as seen from ``module`` (and, for
        ``self.x(...)`` forms, from ``class_name``). ``local_defs``
        carries the enclosing function's nested defs, which shadow
        module symbols."""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "__wrapped__":
            func = func.value
        dotted = _dotted(func)
        if not dotted:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            name = parts[0]
            if local_defs and name in local_defs:
                node = local_defs[name]
                decl = FunctionDecl(
                    qualname=f"{module}:<local>.{name}", module=module,
                    name=name, class_name=class_name, node=node,
                    jit=_decorator_jit_info(node))
                return ResolvedCallee(decl.qualname, decl,
                                      _decl_donation(decl))
            return self.lookup(module, name)
        if parts[0] == "self" and class_name is not None:
            syms = self.modules.get(module)
            if syms is None or len(parts) != 2:
                return None
            decl = syms.methods.get(class_name, {}).get(parts[1])
            if decl is not None:
                res = ResolvedCallee(decl.qualname, decl,
                                     _decl_donation(decl),
                                     bound_self=True)
                return res
            wrap = syms.attr_wrappers.get((class_name, parts[1]))
            if wrap is not None:
                return self._resolve_wrap_attr(syms, class_name,
                                               parts[1], wrap)
            return None
        syms = self.modules.get(module)
        if syms is not None and parts[0] in syms.imports:
            dotted_abs = ".".join([syms.imports[parts[0]]] + parts[1:])
            return self._lookup_dotted(dotted_abs)
        return None

    def _resolve_wrap_attr(self, syms: ModuleSymbols, class_name: str,
                           attr: str, wrap: _JitWrap) -> ResolvedCallee:
        decl = None
        if wrap.target:
            inner = self.lookup(syms.module, wrap.target)
            if inner is not None:
                decl = inner.decl
        donation = None
        if wrap.donate_argnums or wrap.donate_argnames:
            names = set(wrap.donate_argnames)
            if decl is not None:
                params = decl.param_names
                names |= {params[i] for i in wrap.donate_argnums
                          if i < len(params)}
            donation = DonationSignature(
                callee=f"self.{attr}", positions=set(wrap.donate_argnums),
                names=names)
        qual = decl.qualname if decl else \
            f"{syms.module}:{class_name}.{attr}"
        return ResolvedCallee(qual, decl, donation)

    # -- enumeration -------------------------------------------------------
    def iter_functions(self):
        for syms in self.modules.values():
            yield from syms.functions.values()
            for methods in syms.methods.values():
                yield from methods.values()

    def call_edges(self, module: str) -> Set[Tuple[str, str]]:
        """(caller qualname, callee qualname) edges for one module —
        the call-graph surface the resolution unit tests pin."""
        syms = self.modules.get(module)
        if syms is None:
            return set()
        edges: Set[Tuple[str, str]] = set()
        for decl in list(syms.functions.values()) + [
                m for ms in syms.methods.values() for m in ms.values()]:
            local_defs = {n.name: n for n in ast.walk(decl.node)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and n is not decl.node}
            for sub in ast.walk(decl.node):
                if not isinstance(sub, ast.Call):
                    continue
                res = self.resolve_call(module, sub,
                                        class_name=decl.class_name,
                                        local_defs=local_defs)
                if res is not None:
                    edges.add((decl.qualname, res.qualname))
        return edges

    def signature_digest_items(self) -> List[str]:
        """Stable serialization of every donation-relevant interface
        fact; the engine hashes this into the cache key so editing a
        signature anywhere invalidates every module's cached result."""
        items: List[str] = []
        for mod in sorted(self.modules):
            syms = self.modules[mod]
            for decl in sorted(
                    list(syms.functions.values())
                    + [m for ms in syms.methods.values()
                       for m in ms.values()],
                    key=lambda d: d.qualname):
                if decl.jit is not None and (decl.jit.donate_argnums
                                             or decl.jit.donate_argnames):
                    items.append(
                        f"{decl.qualname}|"
                        f"{sorted(decl.jit.donate_argnums)}|"
                        f"{sorted(decl.jit.donate_argnames)}")
            for name in sorted(syms.jit_wrappers):
                w = syms.jit_wrappers[name]
                items.append(f"{mod}:{name}|{sorted(w.donate_argnums)}|"
                             f"{sorted(w.donate_argnames)}|w:{w.target}")
            for (cls, attr) in sorted(syms.attr_wrappers):
                w = syms.attr_wrappers[(cls, attr)]
                items.append(f"{mod}:{cls}.{attr}|"
                             f"{sorted(w.donate_argnums)}|"
                             f"{sorted(w.donate_argnames)}|w:{w.target}")
        return items


def _decl_donation(decl: FunctionDecl) -> Optional[DonationSignature]:
    jit = decl.jit
    if jit is None or not (jit.donate_argnums or jit.donate_argnames):
        return None
    params = decl.param_names
    names = set(jit.donate_argnames)
    names |= {params[i] for i in jit.donate_argnums if i < len(params)}
    return DonationSignature(callee=decl.name,
                             positions=set(jit.donate_argnums),
                             names=names)


def module_name_for_path(path: str) -> str:
    """Dotted module name for a repo-root-relative path; fixture paths
    ("<memory>", tmp files) fall back to their stem so single-module
    analysis still resolves local symbols."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[:-len("/__init__")]
    if p.startswith("<") or "/" not in p:
        return p.rsplit("/", 1)[-1] or p
    return p.replace("/", ".")
