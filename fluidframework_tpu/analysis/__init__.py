"""fluidlint: AST + whole-program static analysis for this tree.

Three rule families guard the silent failure modes of the system
(see docs/static_analysis.md):

* JAX/TPU kernel hygiene (JX*): tracing hazards inside jit-decorated
  functions — Python branching on traced values, host syncs, unrolled
  jnp loops, mutable-global capture, dtype drift, missing donation.
* Server concurrency/robustness (CC*): await-under-lock, blocking calls
  in async code, swallowed exceptions on op-pipeline paths, listener
  registration without a removal path, mutable default arguments.
* Donated-buffer lifecycle (v2, whole-program): a cross-module call
  graph (callgraph.py) + alias/donation dataflow (dataflow.py) prove
  the serving path never reads freed device memory — USE_AFTER_DONATE,
  DONATED_ESCAPE, and the PAGE_ID_DTYPE dtype lattice
  (lifecycle_rules.py).
* Lockset race detection (v3, whole-program): thread-root discovery +
  per-function held-lockset summaries over the server/telemetry tier
  (concurrency_model.py) back SHARED_STATE_NO_LOCK,
  ATOMICITY_CHECK_THEN_ACT, LOCK_ORDER_INVERSION, and
  SIGNAL_WITHOUT_LOCK (race_rules.py), with a runtime verifier in
  testing/lockcheck.py.
* Placement & sharding dataflow (v4, whole-program): a per-binding
  placement lattice (host < replicated < mesh-sharded(PartitionSpec) <
  donated-gone) over the mergetree/server/parallel tiers
  (placement_model.py) backs MESH_DONATION_GATE, UNSPECCED_POOL,
  PSPEC_MISMATCH, HOST_READ_OF_SHARDED, and SHARD_AXIS_DRIFT
  (placement_rules.py), proven against the partition-rule table
  (mergetree/partition_rules.py) with a runtime verifier in
  testing/shardcheck.py.

Run it with ``python -m fluidframework_tpu.analysis [paths]``
(``--changed-only`` for the git-diff-scoped pre-commit pass; warm runs
ride the fingerprint cache in ``.fluidlint_cache.json``). Findings are
suppressed inline with ``# fluidlint: disable=RULE — reason`` or
accepted in the committed baseline (``analysis/baseline.json``); anything
else fails the run, which `make lint-analysis` and
tests/test_static_analysis.py turn into a hard CI gate.
"""

from .engine import (
    AnalysisResult, ModuleContext, ProgramContext, Violation,
    analyze_paths, analyze_source,
)
from .registry import RULES, Rule, all_rules, get_rule, rule
from .baseline import Baseline, DEFAULT_BASELINE_PATH

# Importing the rule modules registers every rule with the registry.
from . import jax_rules as _jax_rules  # noqa: F401
from . import concurrency_rules as _concurrency_rules  # noqa: F401
from . import lifecycle_rules as _lifecycle_rules  # noqa: F401
from . import race_rules as _race_rules  # noqa: F401
from . import placement_rules as _placement_rules  # noqa: F401

__all__ = [
    "AnalysisResult", "Baseline", "DEFAULT_BASELINE_PATH", "ModuleContext",
    "ProgramContext", "RULES", "Rule", "Violation", "all_rules",
    "analyze_paths", "analyze_source", "get_rule", "rule",
]
