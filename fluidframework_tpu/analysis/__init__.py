"""fluidlint: AST-based static analysis for the fluidframework_tpu tree.

Two rule families guard the two silent failure modes of the system
(see docs/static_analysis.md):

* JAX/TPU kernel hygiene (JX*): tracing hazards inside jit-decorated
  functions — Python branching on traced values, host syncs, unrolled
  jnp loops, mutable-global capture, dtype drift, missing donation.
* Server concurrency/robustness (CC*): await-under-lock, blocking calls
  in async code, swallowed exceptions on op-pipeline paths, listener
  registration without a removal path, mutable default arguments.

Run it with ``python -m fluidframework_tpu.analysis [paths]``.  Findings
are suppressed inline with ``# fluidlint: disable=RULE — reason`` or
accepted in the committed baseline (``analysis/baseline.json``); anything
else fails the run, which `make lint-analysis` and
tests/test_static_analysis.py turn into a hard CI gate.
"""

from .engine import AnalysisResult, ModuleContext, Violation, analyze_paths, analyze_source
from .registry import RULES, Rule, all_rules, get_rule, rule
from .baseline import Baseline, DEFAULT_BASELINE_PATH

# Importing the rule modules registers every rule with the registry.
from . import jax_rules as _jax_rules  # noqa: F401
from . import concurrency_rules as _concurrency_rules  # noqa: F401

__all__ = [
    "AnalysisResult", "Baseline", "DEFAULT_BASELINE_PATH", "ModuleContext",
    "RULES", "Rule", "Violation", "all_rules", "analyze_paths",
    "analyze_source", "get_rule", "rule",
]
