"""Whole-program lockset model for the server concurrency tier.

fluidlint v3: the thread/lock discipline that keeps the serving fleet
honest is, as of this layer, machine-checked the same way v2
machine-checks the donated-buffer lifecycle. The model answers, for the
``server/`` and ``telemetry/`` packages:

* **who runs where** — every thread root is discovered from the code
  itself: ``threading.Thread(target=...)`` (including lambda,
  ``functools.partial``, and bound-method targets), executor
  ``submit``/``run_in_executor`` hand-offs, HTTP handler entry points
  (``do_*`` methods of ``*HTTPRequestHandler`` subclasses — the monitor
  /alfred surfaces), and pump callbacks registered via ``subscribe``;
* **what guards what** — lock objects are ``threading.Lock/RLock/
  Condition/Semaphore`` instance attributes (plus module-level locks),
  tracked through ``with`` blocks and ``acquire``/``release`` pairs
  including the try/finally and ``if not lock.acquire(...): return``
  idioms; each function gets a held-lockset effect summary and
  transitive callees inherit the caller's held set (must-held meets by
  intersection across call contexts, Eraser-style);
* **which state is shared** — an instance attribute (or module-level
  container) written from one thread root and read or written from
  another. Per shared attribute the model intersects the locksets over
  all accesses; an empty intersection is the race the
  ``SHARED_STATE_NO_LOCK`` rule reports.

Resolution is name-based and conservative, exactly like the call graph
underneath it (callgraph.py): ``self.m()`` resolves through the class,
``self.merge.extract(...)`` resolves through the instance-attribute
type binding recorded at ``self.merge = MergeLaneStore(...)``, local
``service = self`` aliases resolve through the closure chain (the
monitor's nested HTTP handler), and anything unresolvable models no
effect. Locks passed around as plain function arguments are therefore
tracked only through attribute chains — a documented limit.

Annotations: ``# fluidlint: guarded-by=<attr>`` on an access line
asserts the named lock attribute is held there through a path the
model cannot see; the access's lockset gains that lock (trusted
statically, verified at runtime by ``testing/lockcheck.py``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import _dotted

# The concurrency tier under analysis. "<memory>" keeps fixtures in
# scope (analyze_source paths).
SCOPE_PREFIXES = (
    "fluidframework_tpu/server", "fluidframework_tpu/telemetry",
    "<memory>")

GUARDED_BY_RE = re.compile(
    r"#\s*fluidlint:\s*guarded-by=(?P<attrs>[A-Za-z_][\w,\s]*)")

_LOCK_FACTORY_TAILS = {"Lock", "RLock", "Condition", "Semaphore",
                       "BoundedSemaphore"}
_LOCK_FACTORY_HEADS = {"", "threading", "_threading"}

# Container-mutating method names: a call through the attribute mutates
# the container in place — a WRITE for race purposes.
_MUTATOR_TAILS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "update",
    "setdefault", "add",
}

_CONDITION_OPS = {"wait", "wait_for", "notify", "notify_all"}

# Thread-root forms (discovery; each becomes its own root id).
_EXECUTORISH = ("executor", "pool", "worker")

MAIN_ROOT = "main"


def in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.startswith(s) or f"/{s}" in p for s in SCOPE_PREFIXES)


# -- facts -------------------------------------------------------------------


@dataclass(frozen=True)
class LockDecl:
    key: str      # "module:Class.attr" or "module:name"
    kind: str     # Lock | RLock | Condition | Semaphore | BoundedSemaphore
    owner: str    # Condition's owning lock key ("" = the condition itself)
    path: str
    line: int


@dataclass
class Access:
    attr: str                 # "module:Class.attr" / "module:name"
    kind: str                 # "r" | "w"
    held: Tuple[Tuple[str, int], ...]  # (lock key, acquisition tag)
    node: ast.AST
    init: bool                # inside __init__/__new__: setup, not racing
    in_test_of: Optional[int] = None   # id() of the If whose test holds it
    enclosing_ifs: Tuple[int, ...] = ()

    @property
    def locks(self) -> Set[str]:
        return {k for k, _ in self.held}

    def tag_of(self, lock: str) -> Optional[int]:
        for k, t in self.held:
            if k == lock:
                return t
        return None


@dataclass
class FuncInfo:
    qualname: str
    module: str
    path: str
    class_qual: Optional[str]          # "module:Class" of enclosing class
    node: ast.AST                      # FunctionDef/AsyncFunctionDef/Lambda
    enclosing: Tuple[ast.AST, ...] = ()  # outer function nodes, inner-last
    accesses: List[Access] = field(default_factory=list)
    calls: List[Tuple[str, Tuple[Tuple[str, int], ...], ast.AST]] = \
        field(default_factory=list)
    acquires: List[Tuple[str, Tuple[str, ...], ast.AST]] = \
        field(default_factory=list)
    cond_ops: List[Tuple["LockDecl", str, Tuple[str, ...], ast.AST]] = \
        field(default_factory=list)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


@dataclass(frozen=True)
class ThreadRoot:
    root_id: str     # "thread:<qual>" / "http:<qual>" / "pump:<qual>"
    qualname: str    # the entry function
    form: str        # thread | executor | http-handler | subscribe
    path: str
    line: int


@dataclass
class RaceFinding:
    rule_id: str
    path: str
    node: ast.AST
    message: str
    # line-free identity for the program digest (cache correctness must
    # not depend on line numbers — see ProgramContext.digest).
    ident: str


class ClassInfo:
    def __init__(self, module: str, name: str, path: str):
        self.module = module
        self.name = name
        self.path = path
        self.qualname = f"{module}:{name}"
        self.bases: List[str] = []
        self.methods: Dict[str, ast.AST] = {}
        self.locks: Dict[str, LockDecl] = {}
        self.attr_types: Dict[str, str] = {}   # attr -> class qualname


# -- the model ---------------------------------------------------------------


class ConcurrencyModel:
    """Build once per analyze run (engine.ProgramContext.concurrency)."""

    def __init__(self, index, contexts: Sequence) -> None:
        # contexts: engine.ModuleContext-like (path, source, tree)
        self.index = index
        self.modules: List = [c for c in contexts if in_scope(c.path)]
        self.classes: Dict[str, ClassInfo] = {}     # qualname -> info
        self.module_locks: Dict[str, LockDecl] = {}  # key -> decl
        self.module_globals: Dict[str, Set[str]] = {}  # module -> names
        self.functions: Dict[str, FuncInfo] = {}
        self._by_node: Dict[int, FuncInfo] = {}
        self.roots: List[ThreadRoot] = []
        self._root_ids: Set[str] = set()
        self.guarded_lines: Dict[str, Dict[int, Set[str]]] = {}
        self._ctx_by_path = {c.path: c for c in self.modules}
        self._module_names: Dict[str, str] = {}    # path -> dotted module
        self._lambda_n = 0

        for ctx in self.modules:
            self._module_names[ctx.path] = _module_name(ctx.path)
            self._scan_guarded_by(ctx)
        # Two passes: attr-type bindings (`self.merge = MergeLaneStore(…)`)
        # resolve against the COMPLETE class table — the target class may
        # live in a later-indexed module (or further down the same file).
        self._pending_types: List[Tuple[ClassInfo, str, ast.AST]] = []
        for ctx in self.modules:
            self._index_classes(ctx)
        for info, attr, value in self._pending_types:
            for call in self._constructor_calls(value):
                cq = self._resolve_class_name(info.module,
                                              _dotted(call.func))
                if cq is not None:
                    info.attr_types.setdefault(attr, cq)
                    break
        for ctx in self.modules:
            self._index_functions(ctx)
        for fn in list(self.functions.values()):
            _FunctionPass(self, fn).run()
        self._propagate()
        self.findings: List[RaceFinding] = self._compute_findings()

    # -- guarded-by annotations -------------------------------------------
    def _scan_guarded_by(self, ctx) -> None:
        per_line: Dict[int, Set[str]] = {}
        for i, line in enumerate(ctx.source.splitlines(), start=1):
            m = GUARDED_BY_RE.search(line)
            if m:
                attrs = {a.strip() for a in m.group("attrs").split(",")
                         if a.strip()}
                per_line.setdefault(i, set()).update(attrs)
        if per_line:
            self.guarded_lines[ctx.path] = per_line

    # -- class / lock indexing --------------------------------------------
    def _index_classes(self, ctx) -> None:
        module = self._module_names[ctx.path]
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = ClassInfo(module, node.name, ctx.path)
            info.bases = [_dotted(b) for b in node.bases]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[sub.name] = sub
            # Lock attrs + instance-attr type bindings: any
            # `self.X = ...` assignment in any method (not just
            # __init__ — lazily-built locks count too).
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self._index_self_assign(ctx, info, t.attr,
                                                sub.value, sub)
            self.classes[info.qualname] = info
        # Module-level locks + mutable globals.
        names: Set[str] = set()
        for stmt in ctx.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            name = stmt.targets[0].id
            kind = _lock_factory(stmt.value)
            if kind is not None:
                key = f"{module}:{name}"
                self.module_locks[key] = LockDecl(
                    key=key, kind=kind, owner="", path=ctx.path,
                    line=stmt.lineno)
            elif _mutable_container(stmt.value):
                names.add(name)
        if names:
            self.module_globals[module] = names

    def _index_self_assign(self, ctx, info: ClassInfo, attr: str,
                           value: ast.AST, stmt: ast.stmt) -> None:
        kind = _lock_factory(value)
        if kind is not None:
            owner = ""
            if kind == "Condition" and isinstance(value, ast.Call) \
                    and value.args:
                owner = self._self_attr_name(value.args[0]) or ""
                if owner:
                    owner = f"{info.qualname}.{owner}"
            self.locks_put(info, attr, kind, owner, ctx.path, stmt.lineno)
            return
        # Type binding: `self.merge = MergeLaneStore(...)`, possibly
        # behind an IfExp (`x if x is not None else MergeLaneStore(...)`)
        # — deferred until every module's classes are indexed.
        if any(True for _ in self._constructor_calls(value)):
            self._pending_types.append((info, attr, value))

    def locks_put(self, info: ClassInfo, attr: str, kind: str, owner: str,
                  path: str, line: int) -> None:
        key = f"{info.qualname}.{attr}"
        info.locks[attr] = LockDecl(key=key, kind=kind, owner=owner,
                                    path=path, line=line)

    @staticmethod
    def _constructor_calls(value: ast.AST) -> Iterable[ast.Call]:
        if isinstance(value, ast.Call):
            yield value
        elif isinstance(value, ast.IfExp):
            for side in (value.body, value.orelse):
                if isinstance(side, ast.Call):
                    yield side

    @staticmethod
    def _self_attr_name(node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _resolve_class_name(self, module: str,
                            dotted: str) -> Optional[str]:
        """'MergeLaneStore' / 'mod.Cls' as seen from ``module`` ->
        class qualname, via the import alias table."""
        if not dotted:
            return None
        parts = dotted.split(".")
        syms = self.index.modules.get(module)
        if len(parts) == 1:
            q = f"{module}:{parts[0]}"
            if q in self.classes:
                return q
            if syms is not None and parts[0] in syms.imports:
                target = syms.imports[parts[0]]
                mod, _, cls = target.rpartition(".")
                q = f"{mod}:{cls}"
                return q if q in self.classes else None
            return None
        if syms is not None and parts[0] in syms.imports:
            mod = syms.imports[parts[0]]
            q = f"{mod}:{parts[-1]}"
            return q if q in self.classes else None
        return None

    # -- function table ----------------------------------------------------
    def _index_functions(self, ctx) -> None:
        module = self._module_names[ctx.path]

        def visit(node, qual_parts, class_qual, enclosing):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{module}:{'.'.join(qual_parts + [child.name])}" \
                        if qual_parts else f"{module}:{child.name}"
                    fi = FuncInfo(qualname=qual, module=module,
                                  path=ctx.path, class_qual=class_qual,
                                  node=child, enclosing=tuple(enclosing))
                    self.functions[qual] = fi
                    self._by_node[id(child)] = fi
                    visit(child, qual_parts + [child.name], class_qual,
                          enclosing + [child])
                elif isinstance(child, ast.ClassDef):
                    cq = f"{module}:{child.name}"
                    visit(child, qual_parts + [child.name], cq, enclosing)
                else:
                    visit(child, qual_parts, class_qual, enclosing)

        visit(ctx.tree, [], None, [])
        # HTTP handler entry points: do_* methods of HTTPRequestHandler
        # subclasses run on the server's per-request threads.
        for cls in self.classes.values():
            if cls.path != ctx.path:
                continue
            if not any(b.rsplit(".", 1)[-1].endswith("HTTPRequestHandler")
                       for b in cls.bases):
                continue
            for name, meth in cls.methods.items():
                if name.startswith("do_"):
                    fi = self._by_node.get(id(meth))
                    if fi is not None:
                        self.add_root("http", fi, meth)

    def register_lambda(self, owner: FuncInfo, lam: ast.Lambda) -> FuncInfo:
        """A lambda used as a thread target becomes its own analyzable
        unit (its body runs on the spawned thread)."""
        self._lambda_n += 1
        qual = f"{owner.qualname}.<lambda#{self._lambda_n}>"
        body = ast.Expr(value=lam.body)
        ast.copy_location(body, lam)
        fn = ast.FunctionDef(
            name=f"<lambda#{self._lambda_n}>", args=lam.args, body=[body],
            decorator_list=[], returns=None, type_comment=None)
        fn.type_params = []  # py3.12 field; absent pre-3.12 is fine
        ast.copy_location(fn, lam)
        fi = FuncInfo(qualname=qual, module=owner.module, path=owner.path,
                      class_qual=owner.class_qual, node=fn,
                      enclosing=owner.enclosing + (owner.node,))
        self.functions[qual] = fi
        self._by_node[id(fn)] = fi
        _FunctionPass(self, fi).run()
        return fi

    def add_root(self, form: str, fi: FuncInfo, node: ast.AST) -> None:
        root_id = f"{form}:{fi.qualname}"
        if root_id in self._root_ids:
            return
        self._root_ids.add(root_id)
        self.roots.append(ThreadRoot(
            root_id=root_id, qualname=fi.qualname, form=form,
            path=fi.path, line=getattr(node, "lineno", 0)))

    # -- resolution helpers (used by the per-function pass) ----------------
    def lock_for_expr(self, fn: FuncInfo, expr: ast.AST,
                     local_aliases: Dict[str, str]) -> Optional[LockDecl]:
        """Resolve a context-manager / acquire-receiver expression to a
        known lock: ``self.X``, module-level ``X``, a local alias
        ``lock = self.X``, or a typed chain ``self.a.b``."""
        chain = _chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] in local_aliases:
            chain = local_aliases[parts[0]] + \
                ("." + ".".join(parts[1:]) if len(parts) > 1 else "")
            parts = chain.split(".")
        root_class = self._class_of_root(fn, parts[0])
        if root_class is not None and len(parts) >= 2:
            cls = self.classes.get(root_class)
            if cls is None:
                return None
            if len(parts) == 2:
                return cls.locks.get(parts[1])
            inner = cls.attr_types.get(parts[1])
            if inner is not None and len(parts) == 3:
                icls = self.classes.get(inner)
                if icls is not None:
                    return icls.locks.get(parts[2])
            return None
        if len(parts) == 1:
            key = f"{fn.module}:{parts[0]}"
            return self.module_locks.get(key)
        return None

    def _class_of_root(self, fn: FuncInfo, name: str) -> Optional[str]:
        """'self' (or a closure alias of self) -> enclosing class."""
        if name == "self":
            return fn.class_qual
        return self._self_aliases(fn).get(name)

    def _self_aliases(self, fn: FuncInfo) -> Dict[str, str]:
        """`service = self` bindings visible to ``fn`` (its own body or
        an enclosing function's — the monitor's nested HTTP handler
        reads the service through such a closure alias). Computed once
        per function."""
        cached = getattr(fn, "_self_aliases", None)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        for owner in (fn.node,) + tuple(reversed(fn.enclosing)):
            owner_fi = self._by_node.get(id(owner))
            owner_class = owner_fi.class_qual if owner_fi is not None \
                else fn.class_qual
            for sub in ast.walk(owner):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and owner_class is not None):
                    out.setdefault(sub.targets[0].id, owner_class)
        fn._self_aliases = out
        return out

    def attr_key_for(self, fn: FuncInfo, expr: ast.AST) -> Optional[str]:
        """Shared-state key for a Name/Attribute chain, or None when
        the chain does not resolve to instance/module state."""
        chain = _chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        root_class = self._class_of_root(fn, parts[0])
        if root_class is not None and len(parts) >= 2:
            cls = self.classes.get(root_class)
            if cls is None:
                return None
            attr = parts[1]
            if attr in cls.locks or attr in cls.methods:
                return None
            if len(parts) >= 3 and attr in cls.attr_types:
                inner = self.classes.get(cls.attr_types[attr])
                if inner is not None and parts[2] not in inner.locks \
                        and parts[2] not in inner.methods:
                    return f"{inner.qualname}.{parts[2]}"
                return None
            return f"{root_class}.{attr}"
        if len(parts) == 1 and parts[0] in \
                self.module_globals.get(fn.module, ()):
            return f"{fn.module}:{parts[0]}"
        return None

    def resolve_callable(self, fn: FuncInfo,
                         expr: ast.AST) -> Optional[FuncInfo]:
        """A thread-target / callee expression -> FuncInfo, covering
        bare names (local defs first), self/alias methods, typed attr
        chains, partial(f, ...), and lambdas."""
        if isinstance(expr, ast.Lambda):
            return self.register_lambda(fn, expr)
        if isinstance(expr, ast.Call):
            tail = _dotted(expr.func).rsplit(".", 1)[-1]
            if tail == "partial" and expr.args:
                return self.resolve_callable(fn, expr.args[0])
            return None
        chain = _chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if len(parts) == 1:
            # Nested defs of the enclosing chain shadow module symbols.
            hit = self._nested_defs(fn).get(parts[0])
            if hit is not None:
                return hit
            res = self.index.lookup(fn.module, parts[0])
            if res is not None and res.decl is not None:
                return self._by_node.get(id(res.decl.node))
            return None
        root_class = self._class_of_root(fn, parts[0])
        if root_class is not None:
            cls = self.classes.get(root_class)
            if cls is None:
                return None
            if len(parts) == 2:
                meth = self._lookup_method(cls, parts[1])
                return self._by_node.get(id(meth)) if meth is not None \
                    else None
            if len(parts) == 3 and parts[1] in cls.attr_types:
                inner = self.classes.get(cls.attr_types[parts[1]])
                if inner is not None:
                    meth = self._lookup_method(inner, parts[2])
                    return self._by_node.get(id(meth)) \
                        if meth is not None else None
            return None
        # module alias: counters.increment(...) etc.
        res = self.index.resolve_call(
            fn.module,
            ast.Call(func=expr, args=[], keywords=[]),
            class_name=None)
        if res is not None and res.decl is not None:
            return self._by_node.get(id(res.decl.node))
        return None

    def _nested_defs(self, fn: FuncInfo) -> Dict[str, FuncInfo]:
        """Name -> FuncInfo for defs nested in ``fn`` or its enclosing
        chain (closures shadow module symbols at call sites). Computed
        once per function — resolve_callable runs per call site and
        must not re-walk the body each time."""
        cached = getattr(fn, "_nested_def_map", None)
        if cached is not None:
            return cached
        out: Dict[str, FuncInfo] = {}
        for owner in tuple(fn.enclosing) + (fn.node,):
            for sub in ast.walk(owner):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub is not owner:
                    hit = self._by_node.get(id(sub))
                    if hit is not None:
                        # inner-most wins: later owners are closer
                        out[sub.name] = hit
        fn._nested_def_map = out
        return out

    def _lookup_method(self, cls: ClassInfo, name: str,
                       _seen: Optional[Set[str]] = None
                       ) -> Optional[ast.AST]:
        if name in cls.methods:
            return cls.methods[name]
        seen = _seen or {cls.qualname}
        for base in cls.bases:
            bq = self._resolve_class_name(cls.module, base)
            if bq is not None and bq not in seen:
                seen.add(bq)
                hit = self._lookup_method(self.classes[bq], name, seen)
                if hit is not None:
                    return hit
        return None

    def guard_locks_at(self, fn: FuncInfo, line: int) -> Set[str]:
        """Locks a `# fluidlint: guarded-by=...` comment on this line
        asserts are held (resolved against the function's class, then
        the module)."""
        names = self.guarded_lines.get(fn.path, {}).get(line)
        if not names:
            return set()
        out: Set[str] = set()
        for name in names:
            decl = None
            if fn.class_qual is not None:
                cls = self.classes.get(fn.class_qual)
                if cls is not None:
                    decl = cls.locks.get(name)
            if decl is None:
                decl = self.module_locks.get(f"{fn.module}:{name}")
            if decl is not None:
                out.add(decl.key)
        return out

    # -- propagation -------------------------------------------------------
    def _propagate(self) -> None:
        edges: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        in_deg: Dict[str, int] = {q: 0 for q in self.functions}
        for fn in self.functions.values():
            for callee, held, _node in fn.calls:
                if callee not in self.functions:
                    continue
                edges.setdefault(fn.qualname, []).append(
                    (callee, tuple(sorted({k for k, _ in held}))))
                in_deg[callee] += 1
        root_quals = {r.qualname for r in self.roots}
        seeds = set(root_quals)
        seeds |= {q for q, d in in_deg.items()
                  if d == 0 and q not in root_quals}
        # must-held: meet (intersection) over call contexts; may-held:
        # union (for lock-order pairs, a lock held on ANY path counts).
        self.must_inherited: Dict[str, Optional[frozenset]] = \
            {q: None for q in self.functions}
        self.may_inherited: Dict[str, Set[str]] = \
            {q: set() for q in self.functions}
        # Each work item carries BOTH contexts: the must set meets
        # (intersection) at the callee, the may set unions — and both
        # flow transitively, so a lock held two call levels above an
        # acquisition still forms a lock-order pair even when a mixed
        # unlocked caller empties the must set on the way down.
        work = [(q, frozenset(), frozenset()) for q in sorted(seeds)]
        while work:
            qual, must_ctx, may_ctx = work.pop()
            cur = self.must_inherited[qual]
            new = must_ctx if cur is None else \
                frozenset(cur & must_ctx)
            changed = new != cur
            may = self.may_inherited[qual]
            if not may_ctx <= may:
                may |= may_ctx
                changed = True
            if not changed:
                continue
            self.must_inherited[qual] = new
            for callee, held in edges.get(qual, ()):
                work.append((callee, frozenset(new | set(held)),
                             frozenset(may | set(held))))
        for q, v in self.must_inherited.items():
            if v is None:
                self.must_inherited[q] = frozenset()
        # Per-root reach (plain BFS over call edges).
        plain: Dict[str, List[str]] = {}
        for src, outs in edges.items():
            plain[src] = [c for c, _ in outs]
        self.reach: Dict[str, Set[str]] = {}
        for root in self.roots:
            seen = {root.qualname}
            stack = [root.qualname]
            while stack:
                for nxt in plain.get(stack.pop(), ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            self.reach[root.root_id] = seen
        # main pseudo-root: everything reachable from the non-thread
        # seeds (public surface / unreferenced functions).
        main_seen: Set[str] = set()
        stack = sorted(seeds - root_quals)
        main_seen.update(stack)
        while stack:
            for nxt in plain.get(stack.pop(), ()):
                if nxt not in main_seen:
                    main_seen.add(nxt)
                    stack.append(nxt)
        # Functions unreachable from any seed (cycles with no external
        # entry) default to main.
        for q in self.functions:
            if q not in main_seen and not any(
                    q in r for r in self.reach.values()):
                main_seen.add(q)
        self.reach[MAIN_ROOT] = main_seen

    def roots_of(self, qualname: str) -> Set[str]:
        return {rid for rid, seen in self.reach.items()
                if qualname in seen}

    def effective_locks(self, fn: FuncInfo, access: Access) -> Set[str]:
        inherited = self.must_inherited.get(fn.qualname) or frozenset()
        line = getattr(access.node, "lineno", 0)
        return access.locks | set(inherited) | \
            self.guard_locks_at(fn, line)

    # -- findings ----------------------------------------------------------
    def _compute_findings(self) -> List[RaceFinding]:
        out: List[RaceFinding] = []
        atom_nodes = self._atomicity_findings(out)
        self._shared_state_findings(out, atom_nodes)
        self._lock_order_findings(out)
        self._signal_findings(out)
        out.sort(key=lambda f: (f.path, getattr(f.node, "lineno", 0),
                                f.rule_id, f.message))
        return out

    def _grouped_accesses(self):
        """attr key -> [(FuncInfo, Access, roots, locks)] over non-init
        accesses of functions in scope. An access on a line suppressed
        for SHARED_STATE_NO_LOCK is a DECLARED-SAFE cross-thread access
        (the sanctioned racy-by-design probes): it drops out of the
        shared computation entirely, so the attr's other accessors stay
        quiet instead of inheriting an empty intersection from it."""
        groups: Dict[str, List[Tuple[FuncInfo, Access, Set[str],
                                     Set[str]]]] = {}
        for fn in self.functions.values():
            roots = self.roots_of(fn.qualname)
            suppressed = getattr(self._ctx_by_path.get(fn.path),
                                 "is_suppressed", None)
            for a in fn.accesses:
                if a.init:
                    continue
                if suppressed is not None and suppressed(
                        "SHARED_STATE_NO_LOCK",
                        getattr(a.node, "lineno", 0)):
                    continue
                groups.setdefault(a.attr, []).append(
                    (fn, a, roots, self.effective_locks(fn, a)))
        return groups

    def shared_attrs(self):
        """attr -> (accesses, lockset intersection) for attrs written
        from one root and touched from another."""
        cached = getattr(self, "_shared_cache", None)
        if cached is not None:
            return cached
        out = {}
        for attr, recs in self._grouped_accesses().items():
            write_roots: Set[str] = set()
            all_roots: Set[str] = set()
            for _fn, a, roots, _locks in recs:
                all_roots |= roots
                if a.kind == "w":
                    write_roots |= roots
            if not write_roots or len(all_roots) < 2:
                continue
            if not (all_roots - {MAIN_ROOT}):
                continue  # never touched by a spawned root
            guard = None
            for _fn, _a, _roots, locks in recs:
                guard = set(locks) if guard is None else guard & locks
            out[attr] = (recs, guard or set())
        self._shared_cache = out
        return out

    def _shared_state_findings(self, out: List[RaceFinding],
                               atom_nodes: Set[int]) -> None:
        for attr, (recs, guard) in sorted(self.shared_attrs().items()):
            if guard:
                continue  # a common lock guards every access
            roots = sorted({r for _f, _a, rs, _l in recs for r in rs})
            # The most common lock across accesses, as a fix hint.
            counts: Dict[str, int] = {}
            for _f, _a, _r, locks in recs:
                for lk in locks:
                    counts[lk] = counts.get(lk, 0) + 1
            candidate = max(sorted(counts), key=lambda k: counts[k]) \
                if counts else None
            seen_fns: Set[str] = set()
            for fn, a, _r, locks in sorted(
                    recs, key=lambda r: (r[0].path,
                                         getattr(r[1].node, "lineno", 0))):
                if fn.qualname in seen_fns:
                    continue
                if candidate is not None and candidate in locks:
                    continue
                if id(a.node) in atom_nodes:
                    continue
                seen_fns.add(fn.qualname)
                hint = (f"; other accesses hold `{_disp_lock(candidate)}`"
                        if candidate else "")
                msg = (f"`{_disp_attr(attr)}` is shared across thread "
                       f"roots ({', '.join(_disp_root(r) for r in roots)}) "
                       f"but the lockset intersection over its accesses "
                       f"is empty{hint}; guard this "
                       f"{'write' if a.kind == 'w' else 'read'} or "
                       f"annotate the deliberate pattern "
                       f"(# fluidlint: guarded-by=<attr> / disable)")
                out.append(RaceFinding(
                    "SHARED_STATE_NO_LOCK", fn.path, a.node, msg,
                    ident=f"SHARED_STATE_NO_LOCK|{fn.path}|"
                          f"{fn.qualname}|{attr}|{a.kind}"))

    def _atomicity_findings(self, out: List[RaceFinding]) -> Set[int]:
        """Read-test-write of a shared attr where the guarding lock was
        released between test and act (two distinct acquisitions)."""
        shared = self.shared_attrs()
        flagged: Set[int] = set()
        emitted: Set[Tuple[str, str, int]] = set()
        for attr, (recs, _guard) in sorted(shared.items()):
            by_fn: Dict[str, List[Tuple[FuncInfo, Access]]] = {}
            for fn, a, _r, _l in recs:
                by_fn.setdefault(fn.qualname, []).append((fn, a))
            for qual, pairs in sorted(by_fn.items()):
                tests = [(fn, a) for fn, a in pairs
                         if a.in_test_of is not None and a.kind == "r"]
                writes = [(fn, a) for fn, a in pairs if a.kind == "w"
                          and a.enclosing_ifs]
                for tfn, ta in tests:
                    for wfn, wa in writes:
                        if ta.in_test_of not in wa.enclosing_ifs:
                            continue
                        # Atomic when SOME lock spans both test and
                        # act: inherited from the caller (held across
                        # the whole body), or a shared local lock
                        # taken by the SAME acquisition.
                        inherited = self.must_inherited.get(
                            wfn.qualname) or frozenset()
                        spanning = set(inherited) | {
                            lk for lk in (wa.locks & ta.locks)
                            if wa.tag_of(lk) == ta.tag_of(lk)}
                        if spanning or not wa.locks:
                            # unguarded act is SHARED_STATE territory
                            continue
                        lock = sorted(wa.locks)[0]
                        key = (qual, attr, id(wa.node))
                        if key in emitted:
                            continue
                        emitted.add(key)
                        flagged.add(id(wa.node))
                        flagged.add(id(ta.node))
                        where = ("through two separate acquisitions"
                                 if lock in ta.locks else
                                 "only around the act, not the test")
                        out.append(RaceFinding(
                            "ATOMICITY_CHECK_THEN_ACT", wfn.path,
                            wa.node,
                            f"check-then-act on `{_disp_attr(attr)}`: "
                            f"`{_disp_lock(lock)}` is held {where} — "
                            f"the lock is released (or not yet taken) "
                            f"between test and act, so another thread "
                            f"can invalidate the test; widen one "
                            f"critical section over both",
                            ident=f"ATOMICITY_CHECK_THEN_ACT|"
                                  f"{wfn.path}|{qual}|{attr}"))
        return flagged

    def _lock_order_findings(self, out: List[RaceFinding]) -> None:
        # direction (A, B) -> first (path, node, qual) that acquired B
        # while holding A.
        pairs: Dict[Tuple[str, str], Tuple[str, ast.AST, str]] = {}
        for fn in sorted(self.functions.values(),
                         key=lambda f: (f.path,
                                        getattr(f.node, "lineno", 0))):
            may = self.may_inherited.get(fn.qualname, set())
            for lock, held_before, node in fn.acquires:
                for prior in sorted(set(held_before) | may):
                    if prior == lock:
                        continue
                    pairs.setdefault((prior, lock),
                                     (fn.path, node, fn.qualname))
        for (a, b), (path, node, qual) in sorted(pairs.items()):
            if a >= b or (b, a) not in pairs:
                continue
            rpath, rnode, rqual = pairs[(b, a)]
            for (l1, l2, p, n, q, other_q) in (
                    (a, b, path, node, qual, rqual),
                    (b, a, rpath, rnode, rqual, qual)):
                out.append(RaceFinding(
                    "LOCK_ORDER_INVERSION", p, n,
                    f"`{_disp_lock(l2)}` is acquired while holding "
                    f"`{_disp_lock(l1)}` here, but `{other_q}` acquires "
                    f"them in the opposite order — two threads taking "
                    f"one lock each deadlock; pick one global order",
                    ident=f"LOCK_ORDER_INVERSION|{p}|{q}|{l1}|{l2}"))

    def _signal_findings(self, out: List[RaceFinding]) -> None:
        for fn in self.functions.values():
            inherited = self.must_inherited.get(fn.qualname) or frozenset()
            for decl, op, held, node in fn.cond_ops:
                eff = set(held) | set(inherited) | \
                    self.guard_locks_at(fn, getattr(node, "lineno", 0))
                owner = decl.owner or decl.key
                if owner in eff or decl.key in eff:
                    continue
                out.append(RaceFinding(
                    "SIGNAL_WITHOUT_LOCK", fn.path, node,
                    f"`{_disp_lock(decl.key)}.{op}()` outside its "
                    f"owning lock `{_disp_lock(owner)}`: "
                    f"notify/wait without the lock raises "
                    f"RuntimeError or misses the wakeup entirely; "
                    f"wrap the call in `with "
                    f"{_disp_lock(owner).rsplit('.', 1)[-1]}:`",
                    ident=f"SIGNAL_WITHOUT_LOCK|{fn.path}|"
                          f"{fn.qualname}|{decl.key}|{op}"))

    # -- engine surface ----------------------------------------------------
    def findings_for(self, path: str) -> List[RaceFinding]:
        return [f for f in self.findings if f.path == path]

    def reach_expansion(self, changed: Set[str]) -> Set[str]:
        """Files whose race findings a change to ``changed`` can alter:
        the full file set of every spawned-thread root whose reach
        touches a changed file, PLUS every file accessing a shared
        attribute (or a lock-order inversion pair) that a changed file
        also touches — a main-side file can flip another file's
        lockset-intersection verdict without sharing any spawned root's
        call graph (locksets are whole-program)."""
        out: Set[str] = set(changed)
        groups: List[Set[str]] = []
        for root in self.roots:
            files = {self.functions[q].path
                     for q in self.reach.get(root.root_id, ())
                     if q in self.functions}
            files.add(root.path)
            groups.append(files)
        for recs, _guard in self.shared_attrs().values():
            groups.append({fn.path for fn, _a, _r, _l in recs})
        by_lock_pair: Dict[Tuple[str, str], Set[str]] = {}
        for fn in self.functions.values():
            for lock, held_before, _node in fn.acquires:
                for prior in held_before:
                    if prior != lock:
                        pair = tuple(sorted((prior, lock)))
                        by_lock_pair.setdefault(pair, set()).add(fn.path)
        groups.extend(by_lock_pair.values())
        for files in groups:
            if files & changed:
                out |= files
        return out

    def digest_items(self) -> List[str]:
        """Line-number-free serialization of everything that shapes the
        race findings; folded into the program digest so a concurrency-
        relevant edit anywhere invalidates every module's cached
        result, while pure line drift keeps the cache warm."""
        items = [f"cc-lock|{d.key}|{d.kind}|{d.owner}"
                 for d in self.module_locks.values()]
        for cls in self.classes.values():
            for d in cls.locks.values():
                items.append(f"cc-lock|{d.key}|{d.kind}|{d.owner}")
        items.extend(f"cc-root|{r.root_id}|{r.form}" for r in self.roots)
        items.extend(f"cc-find|{f.ident}|{f.message}"
                     for f in self.findings)
        return sorted(items)

    def inferred_guards(self, class_qual: str) -> Dict[str, str]:
        """attr name -> lock attr name for a class's shared attributes
        whose lockset intersection is a single same-class lock — the
        statically inferred discipline testing/lockcheck.py verifies at
        runtime."""
        out: Dict[str, str] = {}
        prefix = class_qual + "."
        for attr, (_recs, guard) in self.shared_attrs().items():
            if not attr.startswith(prefix):
                continue
            same_class = sorted(lk for lk in guard
                                if lk.startswith(prefix))
            if len(same_class) == 1:
                out[attr[len(prefix):]] = \
                    same_class[0][len(prefix):]
        return out


# -- the per-function pass ---------------------------------------------------


class _FunctionPass:
    """One statement-ordered walk over one function body, tracking the
    locally held lockset (with tags identifying each acquisition) and
    recording accesses, call edges, lock-order acquires, condition ops,
    and thread spawns onto the FuncInfo."""

    def __init__(self, model: ConcurrencyModel, fn: FuncInfo):
        self.model = model
        self.fn = fn
        self.is_init = fn.name in ("__init__", "__new__")
        self.aliases: Dict[str, str] = {}  # local name -> chain it aliases
        self._if_stack: List[int] = []

    def run(self) -> None:
        self._block(self.fn.node.body, [])

    # -- statements --------------------------------------------------------
    def _block(self, stmts, held: List[Tuple[str, int]]) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held: List[Tuple[str, int]]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate analyzable units
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._expr(item.context_expr, held)
                decl = self.model.lock_for_expr(self.fn, item.context_expr,
                                                self.aliases)
                if decl is not None:
                    self._record_acquire(decl.key, held, stmt)
                    held.append((decl.key, id(stmt)))
                    pushed += 1
            self._block(stmt.body, held)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(stmt, ast.If):
            self._if_test(stmt, held)
            self._if_stack.append(id(stmt))
            body_held = list(held)
            self._block(stmt.body, body_held)
            else_held = list(held)
            self._block(stmt.orelse, else_held)
            self._if_stack.pop()
            # Continuation sees the locks held on every NON-terminating
            # outcome (an `if not lock.acquire(...): return` body
            # terminates, so the test's acquire survives through the
            # fall-through side).
            t_body = _terminates(stmt.body)
            t_else = bool(stmt.orelse) and _terminates(stmt.orelse)
            if t_body and not t_else:
                held[:] = else_held
            elif t_else and not t_body:
                held[:] = body_held
            else:
                held[:] = [h for h in body_held if h in else_held]
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held)
            for handler in stmt.handlers:
                h_held = list(held)
                self._block(handler.body, h_held)
            self._block(stmt.orelse, held)
            self._block(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._block(stmt.body, list(held))
            self._block(stmt.orelse, list(held))
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self._block(stmt.body, list(held))
            self._block(stmt.orelse, list(held))
            return
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, held)
            self._maybe_alias(stmt)
            for t in stmt.targets:
                self._target(t, held)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, held)
            base = stmt.target.value \
                if isinstance(stmt.target, ast.Subscript) else stmt.target
            key = self.model.attr_key_for(self.fn, base)
            if key is not None:
                self._access(key, "r", stmt.target, held)
                self._access(key, "w", stmt.target, held)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, held)
                self._target(stmt.target, held)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                key = self.model.attr_key_for(self.fn, base)
                if key is not None:
                    self._access(key, "w", t, held)
            return
        if isinstance(stmt, (ast.Return, ast.Expr, ast.Assert,
                             ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, held)
            return
        # default: walk child expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    def _if_test(self, stmt: ast.If, held) -> None:
        marker = id(stmt)
        self._expr(stmt.test, held, in_test_of=marker)

    def _maybe_alias(self, stmt: ast.Assign) -> None:
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0],
                                                    ast.Name):
            return
        chain = _chain(stmt.value)
        if chain is not None:
            self.aliases[stmt.targets[0].id] = chain
        else:
            self.aliases.pop(stmt.targets[0].id, None)

    def _target(self, target: ast.AST, held) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._target(el, held)
            return
        if isinstance(target, ast.Subscript):
            self._expr(target.slice, held)
            key = self.model.attr_key_for(self.fn, target.value)
            if key is not None:
                self._access(key, "w", target, held)
            return
        key = self.model.attr_key_for(self.fn, target)
        if key is not None:
            self._access(key, "w", target, held)

    # -- expressions -------------------------------------------------------
    def _expr(self, expr: ast.AST, held,
              in_test_of: Optional[int] = None) -> None:
        for node in self._walk_expr(expr):
            if isinstance(node, ast.Call):
                self._call(node, held)
            elif isinstance(node, (ast.Name, ast.Attribute)):
                if isinstance(getattr(node, "ctx", None), ast.Load):
                    key = self.model.attr_key_for(self.fn, node)
                    if key is not None:
                        self._access(key, "r", node, held,
                                     in_test_of=in_test_of)

    def _walk_expr(self, expr: ast.AST):
        """Pre-order walk that treats a full attr chain as ONE node
        (no per-component re-reporting) and skips deferred bodies."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            yield node
            if isinstance(node, ast.Attribute):
                # chain handled whole at the top node; only descend
                # past the chain's root expression when it is complex
                # (a call/subscript), never into Name/Attribute links.
                cur = node
                while isinstance(cur, ast.Attribute):
                    cur = cur.value
                if not isinstance(cur, ast.Name):
                    stack.append(cur)
                continue
            if isinstance(node, ast.Call):
                stack.extend(node.args)
                stack.extend(kw.value for kw in node.keywords)
                # The callee: a Name/Attribute chain is resolved whole
                # by _call (which also records the receiver access, as
                # a write for mutator tails); only a COMPLEX chain root
                # (subscript, nested call) descends here.
                if not isinstance(node.func, (ast.Name, ast.Attribute)):
                    stack.append(node.func)
                elif isinstance(node.func, ast.Attribute):
                    cur = node.func.value
                    while isinstance(cur, ast.Attribute):
                        cur = cur.value
                    if not isinstance(cur, ast.Name):
                        stack.append(cur)
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _call(self, call: ast.Call, held) -> None:
        func = call.func
        dotted = _dotted(func)
        tail = dotted.rsplit(".", 1)[-1] if dotted else ""
        # 1. lock protocol
        if isinstance(func, ast.Attribute):
            decl = self.model.lock_for_expr(self.fn, func.value,
                                            self.aliases)
            if decl is not None:
                if tail == "acquire":
                    self._record_acquire(decl.key, held, call)
                    held.append((decl.key, id(call)))
                    return
                if tail == "release":
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == decl.key:
                            del held[i]
                            break
                    return
                if decl.kind == "Condition" and tail in _CONDITION_OPS:
                    self.fn.cond_ops.append(
                        (decl, tail,
                         tuple(sorted({k for k, _ in held})), call))
                    return
                if tail == "locked":
                    return
        # 2. thread / callback spawns
        self._maybe_spawn(call, tail, dotted)
        # 3. receiver mutation (self.items.append(...))
        if isinstance(func, ast.Attribute):
            key = self.model.attr_key_for(self.fn, func.value)
            if key is not None:
                kind = "w" if tail in _MUTATOR_TAILS else "r"
                self._access(key, kind, call, held)
        # 4. call edge
        callee = self._resolve_call_edge(call)
        if callee is not None:
            self.fn.calls.append(
                (callee.qualname,
                 tuple((k, t) for k, t in held), call))

    def _maybe_spawn(self, call: ast.Call, tail: str,
                     dotted: str) -> None:
        target_expr = None
        form = None
        if tail == "Thread" and (dotted.rsplit(".", 1)[0]
                                 in ("threading", "_threading", "Thread")
                                 or dotted == "Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr, form = kw.value, "thread"
        elif tail == "run_in_executor" and len(call.args) >= 2:
            target_expr, form = call.args[1], "executor"
        elif tail == "submit" and call.args:
            head = dotted.rsplit(".", 2)[-2].lower() if "." in dotted \
                else ""
            if any(tok in head for tok in _EXECUTORISH):
                target_expr, form = call.args[0], "executor"
        elif tail == "subscribe" and call.args:
            cb = call.args[-1]
            for kw in call.keywords:
                if kw.arg == "fn":
                    cb = kw.value
            target_expr, form = cb, "subscribe"
        if target_expr is None:
            return
        target = self.model.resolve_callable(self.fn, target_expr)
        if target is not None:
            self.model.add_root(form, target, call)

    def _resolve_call_edge(self, call: ast.Call) -> Optional[FuncInfo]:
        func = call.func
        if isinstance(func, (ast.Name, ast.Attribute)):
            return self.model.resolve_callable(self.fn, func)
        return None

    # -- recording ---------------------------------------------------------
    def _access(self, key: str, kind: str, node: ast.AST, held,
                in_test_of: Optional[int] = None) -> None:
        self.fn.accesses.append(Access(
            attr=key, kind=kind,
            held=tuple((k, t) for k, t in held),
            node=node, init=self.is_init, in_test_of=in_test_of,
            enclosing_ifs=tuple(self._if_stack)))

    def _record_acquire(self, key: str, held, node: ast.AST) -> None:
        self.fn.acquires.append(
            (key, tuple(sorted({k for k, _ in held})), node))


# -- small helpers -----------------------------------------------------------


def _chain(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    if len(parts) > 4:
        return None
    return ".".join(reversed(parts))


def _lock_factory(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    head, _, tail = dotted.rpartition(".")
    if tail in _LOCK_FACTORY_TAILS and head in _LOCK_FACTORY_HEADS:
        return tail
    return None


def _mutable_container(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(value, ast.Call):
        tail = _dotted(value.func).rsplit(".", 1)[-1]
        return tail in ("list", "dict", "set", "deque", "defaultdict",
                        "OrderedDict", "Counter")
    return False


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _disp_attr(key: str) -> str:
    """'module:Class.attr' -> 'Class.attr' for messages."""
    return key.rsplit(":", 1)[-1]


def _disp_lock(key: Optional[str]) -> str:
    return key.rsplit(":", 1)[-1] if key else "<none>"


def _disp_root(root_id: str) -> str:
    if root_id == MAIN_ROOT:
        return "main"
    form, _, qual = root_id.partition(":")
    return f"{form}:{qual.rsplit(':', 1)[-1]}"


def _module_name(path: str) -> str:
    from .callgraph import module_name_for_path
    return module_name_for_path(path)
