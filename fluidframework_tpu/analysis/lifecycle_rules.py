"""Donated-buffer lifecycle rules (fluidlint v2, whole-program).

Three rule families over the callgraph + dataflow layer
(callgraph.py / dataflow.py), guarding the donated-dispatch discipline
that has produced this repo's costliest bug class three PRs running
(docs/serving_pipeline.md R6, docs/static_analysis.md):

* ``USE_AFTER_DONATE`` — a binding (or alias: tuple leaf, pytree-carry
  member, attribute chain) whose buffer went to a ``donate_argnums``
  position is read again before reassignment. The PR 7 burst-fallback
  shape — an except handler re-reading the donated scan carry — is the
  seeded regression fixture.
* ``DONATED_ESCAPE`` — a donated binding stored into ``self.*`` state
  that outlives the dispatch (the PR 5 stale-lane-plane shape), either
  stored-then-donated or donated-then-stored.
* ``PAGE_ID_DTYPE`` (v2) — the int16/int32/int64/uint32 dtype lattice
  propagated through ``astype``/``asarray``/arithmetic/subscripts, so a
  page id widened or narrowed through an intermediate binding is caught
  where the old regex (which only saw page-NAMED assignments) was
  blind. Scope, triggers, and message shape are unchanged from v1.

Sanctioned patterns are modeled as guards, not blanket suppressions:
``serve_window_keep``-style non-donating variants simply resolve to a
smaller donation signature; the burst fallback's
liveness-probe-then-reraise (``tree_leaves``/``.is_deleted()``, also
through ``map(_gone, states)``) is recognized as a metadata read; and
the canonical ``state, ys = step(state, xs)`` rebind kills the donation
in the same statement.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set, Tuple

from .engine import ModuleContext, Violation
from .registry import rule
from .jax_rules import _scan_scope

# Page-table indices must ride the canonical int32 page-id dtype
# (mergetree.constants.PAGE_ID_DTYPE); see the v1 rationale. The name
# trigger and kernel surface are unchanged from v1 — only the engine
# underneath moved from regex matching to the dataflow lattice.
_PAGE_NAME_RE = re.compile(
    r"(^|_)(page_?(ids?|tables?)|pids)($|_)", re.IGNORECASE)

_PAGED_KERNEL_NAMES = {
    "gather_pages", "scatter_pages", "rollback_pages", "apply_ops_paged",
    "compact_pages", "compact_extract_paged", "serve_paged_burst",
}


def _program_for(ctx: ModuleContext):
    """The whole-program context. analyze_paths attaches one spanning
    every analyzed module; analyze_source (fixtures) gets a
    single-module program built on demand."""
    program = getattr(ctx, "program", None)
    if program is None:
        from .engine import ProgramContext
        program = ProgramContext([ctx])
        ctx.program = program
    return program


def _enclosing_class(ctx: ModuleContext, fn: ast.AST) -> Optional[str]:
    cur = ctx.parents.get(fn)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def nested in a method still sees that method's class
            cur = ctx.parents.get(cur)
            continue
        cur = ctx.parents.get(cur)
    return None


def _jit_wrapped_defs(ctx: ModuleContext, program) -> Set[str]:
    """Names of module functions consumed by an assignment jit wrapper
    (``serve_burst = partial(jax.jit, …)(_serve_burst)``): their bodies
    are traced code exactly like decorator-jitted ones."""
    from .callgraph import module_name_for_path
    mod = program.index.modules.get(module_name_for_path(ctx.path))
    if mod is None:
        return set()
    return {w.target for w in mod.jit_wrappers.values() if w.target}


def _inside_jit(ctx: ModuleContext, fn: ast.AST,
                wrapped_names: Set[str]) -> bool:
    cur: Optional[ast.AST] = fn
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if cur in ctx.jit_functions or cur.name in wrapped_names:
                return True
        cur = ctx.parents.get(cur)
    return False


def _module_findings(ctx: ModuleContext):
    """Run the dataflow pass once per module; the three rules below
    each filter their kind. Cached on the context because the registry
    invokes every rule's check() independently."""
    cached = getattr(ctx, "_lifecycle_findings", None)
    if cached is not None:
        return cached
    from .callgraph import module_name_for_path
    from .dataflow import FunctionDataflow
    program = _program_for(ctx)
    module_name = module_name_for_path(ctx.path)
    wrapped = _jit_wrapped_defs(ctx, program)
    findings: List[Tuple[str, ast.AST, str]] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        df = FunctionDataflow(
            fn, module_name, _enclosing_class(ctx, fn),
            program.index, program.summaries,
            page_name_re=_PAGE_NAME_RE,
            paged_kernel_names=_PAGED_KERNEL_NAMES,
            # Donation is a CALL-BOUNDARY effect: inside a traced body
            # jax ignores nested donation, so only host functions get
            # lifecycle tracking (dtype checks still run everywhere).
            track_donation=not _inside_jit(ctx, fn, wrapped))
        for f in df.run():
            findings.append((f.kind, f.node, f.message))
    # Module-level statements (page-table staging helpers built at
    # import time): dtype lattice only, no donation semantics.
    mod_fn = ast.FunctionDef(
        name="<module>", body=[s for s in ctx.tree.body
                               if not isinstance(s, (ast.FunctionDef,
                                                     ast.AsyncFunctionDef,
                                                     ast.ClassDef))],
        decorator_list=[],
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]))
    df = FunctionDataflow(mod_fn, module_name, None, program.index,
                          program.summaries,
                          page_name_re=_PAGE_NAME_RE,
                          paged_kernel_names=_PAGED_KERNEL_NAMES,
                          track_donation=False)
    for f in df.run():
        findings.append((f.kind, f.node, f.message))
    ctx._lifecycle_findings = findings
    return findings


def _emit(ctx: ModuleContext, kind: str) -> Iterator[Violation]:
    if not _scan_scope(ctx):
        return
    seen: Set[Tuple[int, int, str]] = set()
    for k, node, message in _module_findings(ctx):
        if k != kind:
            continue
        key = (getattr(node, "lineno", 0),
               getattr(node, "col_offset", 0), message)
        if key in seen:
            continue
        seen.add(key)
        yield ctx.violation(kind, node, message)


@rule("USE_AFTER_DONATE",
      "Read of a donated binding (or alias) after the donating "
      "dispatch, before reassignment",
      family="jax",
      rationale="donate_argnums hands the buffer to XLA: the dispatch "
                "may reuse or free it immediately, so a later read "
                "returns garbage or raises on a deleted array — the PR 7 "
                "burst-fallback bug class. Rebind from the call result, "
                "or probe liveness (tree_leaves/.is_deleted()) and "
                "re-raise instead of falling back onto the carry.")
def use_after_donate(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "USE_AFTER_DONATE")


@rule("DONATED_ESCAPE",
      "Donated binding stored into self.*/module state that outlives "
      "the dispatch",
      family="jax",
      rationale="Instance state holding a donated plane is a time bomb: "
                "the next reader (often a whole flush later) sees freed "
                "or recycled device memory — the PR 5 stale-lane-plane "
                "shape. Store the call's RESULT, or rebind the attribute "
                "before returning.")
def donated_escape(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "DONATED_ESCAPE")


@rule("PAGE_ID_DTYPE",
      "Page-table index built, cast, or propagated with a non-int32 "
      "integer dtype",
      family="jax",
      rationale="Page ids are the canonical int32 device index "
                "(mergetree.constants.PAGE_ID_DTYPE): int64 doubles "
                "every page-table transfer, int16 wraps past 32k pages "
                "into another document's page, and unsigned 32-bit "
                "destroys the -1 padding sentinel. v2 propagates the "
                "dtype through astype/asarray/arithmetic, so the drift "
                "is caught even through intermediate bindings.")
def page_id_dtype(ctx: ModuleContext) -> Iterator[Violation]:
    yield from _emit(ctx, "PAGE_ID_DTYPE")
