"""Whole-program placement & sharding dataflow (fluidlint v4).

The placement lattice, per binding::

    host  <  device-replicated  <  mesh-sharded(PartitionSpec)  <  donated-gone

v2 machine-checks the donated-buffer lifecycle and v3 the thread/lock
discipline; this layer machine-checks the MESH discipline of the
``mergetree/``, ``server/`` and ``parallel/`` packages: where every
serving pytree lives, under which ``PartitionSpec``, and whether the
jit dispatch boundaries it crosses are compatible with that placement.
The model indexes, per function (module top level is its own unit):

* **mesh handles** — ``make_mesh(...)`` / ``Mesh(..., axis_names=...)``
  construction sites; axis-name literals union into the program-wide
  mesh-axes set (``{"dp", "sp"}`` for this repo's meshes);
* **spec literals** — ``PartitionSpec``/``P`` calls (resolved through
  the import alias table, so an unrelated local ``P`` stays invisible);
* **placement transfers** — ``device_put(x, NamedSharding(...))``,
  ``with_sharding_constraint``, and the house helpers ``shard_docs`` /
  ``replicate`` / ``place_with_rules`` (the rule-table engine in
  ``mergetree/partition_rules.py``);
* **dispatch boundaries** — jit/pjit wrap sites with ``donate_argnums``
  / ``in_shardings`` (function-local wraps tracked here; module-level
  wraps resolve through callgraph.ProgramIndex, so a donating callee
  two modules away still gates).

**Definite vs may.** A placement recorded under a conditional
(``if mesh is not None: ...``, loop/try bodies) is a MAY placement and
never fires a rule — the production tier's single-chip/mesh dual-mode
construction (``self._place`` returning the tree unchanged off-mesh)
stays quiet without suppressions. Only DEFINITE placements (straight-
line code at function or module top level) participate. That is the
documented soundness trade of this layer: the conditional half is
covered dynamically by ``testing/shardcheck.py``, which asserts actual
``.sharding`` against the same rule table while the mesh tests and
SOAK trials run.

The rule table itself (``mergetree/partition_rules.py``) is part of the
model's digest: the ``*_RULES`` assignments fold in via ``ast.dump``
(no line numbers), so editing a spec invalidates every module's cached
result while pure line drift keeps the cache warm — the same contract
the race detector's lockset facts follow.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import REPO_ROOT, _dotted

# The mesh tier under analysis. "<memory>" keeps fixtures in scope
# (analyze_source paths).
SCOPE_PREFIXES = (
    "fluidframework_tpu/mergetree", "fluidframework_tpu/server",
    "fluidframework_tpu/parallel", "<memory>")

#: Files whose edits can flip ANY module's placement verdict: the mesh
#: helpers and the partition-rule table (relative to the repo root).
HELPER_FILES = (
    "fluidframework_tpu/parallel/mesh.py",
    "fluidframework_tpu/mergetree/partition_rules.py",
)

RULE_TABLE_REL = "fluidframework_tpu/mergetree/partition_rules.py"

# Lattice levels.
HOST, REPLICATED, SHARDED, DONATED = \
    "host", "replicated", "sharded", "donated"

# Placement-helper call tails: shard_docs/replicate/place_with_rules are
# the sanctioned house helpers (parallel/mesh.py, mergetree/
# partition_rules.py); their callees never count as mesh DISPATCHES.
_PLACE_SHARDED_TAILS = {"shard_docs", "place_with_rules"}
_PLACE_REPLICATED_TAILS = {"replicate"}
_PLACEMENT_TAILS = (_PLACE_SHARDED_TAILS | _PLACE_REPLICATED_TAILS
                    | {"device_put", "with_sharding_constraint",
                       "NamedSharding", "ensure_placement",
                       "match_partition_rules", "resolved_spec_table",
                       "assert_placement", "verify_store",
                       "placement_report", "named_leaves", "adopt_pool",
                       "instrument", "tree_map"})

_HOST_CTOR_TAILS = {"zeros", "ones", "full", "empty", "arange",
                    "zeros_like", "ones_like", "full_like"}

# Host-read forms on a mesh-sharded binding (each one devices-gathers
# the whole array through a blocking transfer).
_HOST_READ_METHOD_TAILS = {"item", "tolist"}
_HOST_READ_FN_NAMES = {"int", "float", "bool"}
_HOST_READ_NP_TAILS = {"asarray", "array"}
_NP_HEADS = {"np", "numpy", "onp"}

# Enclosing-function names sanctioned to host-read sharded state (the
# gather helpers; matches the serving tier's naming convention).
SANCTIONED_READ_RE = re.compile(
    r"(gather|to_host|host_read|device_get|fetch|debug|dump)",
    re.IGNORECASE)

# Lane/page-pool pytree naming convention (UNSPECCED_POOL subjects).
POOL_NAME_RE = re.compile(r"(^|_)pools?$")

_MESH_CTOR_TAILS = {"Mesh", "make_mesh"}

DEFAULT_MESH_AXES = frozenset({"dp", "sp"})


def in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.startswith(s) or f"/{s}" in p for s in SCOPE_PREFIXES)


# -- facts -------------------------------------------------------------------


@dataclass(frozen=True)
class PlacementFinding:
    rule_id: str
    path: str
    node: ast.AST
    message: str
    # line-free identity for the program digest (cache correctness must
    # not depend on line numbers — see ProgramContext.digest).
    ident: str


@dataclass
class _Bind:
    """One name's point in the lattice inside one analyzed unit."""
    kind: str = "array"          # array | mesh | spec | ns
    level: str = HOST
    spec: Optional[str] = None   # canonical "P('dp', None)" when known
    rank: Optional[int] = None   # syntactically known rank, else None
    definite: bool = False       # placed on a straight-line path
    node: Optional[ast.AST] = None
    dispatch_spec: Optional[str] = None  # last in_shardings it crossed


@dataclass
class _LocalJit:
    """``step = jax.jit(fn, donate_argnums=..., in_shardings=...)``
    bound inside the unit being walked (module-level wraps resolve
    through ProgramIndex instead)."""
    donate: Set[int] = field(default_factory=set)
    in_spec: Optional[str] = None


# -- spec literal parsing ----------------------------------------------------


def _pspec_alias_ok(model, module: str, name: str) -> bool:
    """Is bare ``name`` a PartitionSpec binding in ``module``? True for
    the canonical import aliases; resolved through the module's import
    table so unrelated helpers named ``P`` stay invisible."""
    if name == "PartitionSpec":
        return True
    syms = model.index.modules.get(module)
    if syms is None:
        return name in ("P", "PS")
    target = syms.imports.get(name, "")
    return target.endswith(".PartitionSpec")


def parse_spec(call: ast.Call):
    """A PartitionSpec literal -> (canonical string, axis names,
    arity). Any non-literal argument (starred specs, names) makes the
    WHOLE spec unknown — (None, axes, None) — the conservative quiet
    choice; literal axis names still feed PSPEC_MISMATCH."""
    parts: List[str] = []
    axes: Set[str] = set()
    known = True
    if any(isinstance(a, ast.Starred) for a in call.args) or call.keywords:
        known = False
    for arg in call.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            axes.add(arg.value)
            parts.append(repr(arg.value))
        elif isinstance(arg, ast.Constant) and arg.value is None:
            parts.append("None")
        elif isinstance(arg, (ast.Tuple, ast.List)):
            sub = []
            for el in arg.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    axes.add(el.value)
                    sub.append(repr(el.value))
                else:
                    known = False
            parts.append("(" + ", ".join(sub) + ")")
        else:
            known = False
    if not known:
        return None, axes, None
    return "P(" + ", ".join(parts) + ")", axes, len(parts)


def _tail(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _jit_callee(value: ast.AST) -> Optional[ast.Call]:
    """The jit-application call of a wrap expression: ``jax.jit(f, …)``
    or ``functools.partial(jax.jit, …)(f)``; None otherwise."""
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    if dotted in ("jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"):
        return value
    if isinstance(value.func, ast.Call):
        inner = value.func
        if _tail(_dotted(inner.func)) == "partial" and inner.args and \
                _dotted(inner.args[0]) in ("jax.jit", "jit", "pjit"):
            # kwargs live on the partial; the outer call applies it.
            return ast.Call(func=inner.args[0], args=list(value.args),
                            keywords=list(inner.keywords))
    return None


# -- rule-table digest -------------------------------------------------------


def rule_table_digest(contexts: Sequence = ()) -> str:
    """Semantic digest of the ``*_RULES`` assignments in
    mergetree/partition_rules.py: ``ast.dump`` excludes line numbers,
    so editing a spec invalidates while comment edits / line drift stay
    warm. Reads the analyzed context when present (fixture trees),
    falling back to the repo checkout."""
    source = None
    for ctx in contexts:
        if ctx.path.replace("\\", "/").endswith(
                "mergetree/partition_rules.py"):
            source = ctx.source
            break
    if source is None:
        try:
            source = (REPO_ROOT / RULE_TABLE_REL).read_text()
        except OSError:
            return "absent"
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return "unparsable"
    dumps = []
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id.endswith("_RULES"):
            dumps.append(ast.dump(stmt))
    return hashlib.sha1("\n".join(dumps).encode()).hexdigest()[:16]


# -- the model ---------------------------------------------------------------


class PlacementModel:
    """Build once per analyze run (engine.ProgramContext.placement)."""

    def __init__(self, index, contexts: Sequence) -> None:
        self.index = index
        self.contexts = list(contexts)
        self.modules = [c for c in self.contexts if in_scope(c.path)]
        self.mesh_axes: Set[str] = set()
        self.mesh_sites: List[Tuple[str, str]] = []  # (path, dotted form)
        self.fact_files: Set[str] = set()
        self.findings: List[PlacementFinding] = []
        self._module_names: Dict[str, str] = {
            c.path: _module_name(c.path) for c in self.modules}
        self.table_digest = rule_table_digest(self.contexts)
        # Pass 1: the program-wide mesh-axes union — spec literals in
        # any module check against EVERY mesh the program builds.
        for ctx in self.modules:
            self._scan_meshes(ctx)
        if not self.mesh_axes:
            self.mesh_axes = set(DEFAULT_MESH_AXES)
        # Pass 2: per-unit lattice walks.
        for ctx in self.modules:
            self._walk_module(ctx)
        self.findings.sort(
            key=lambda f: (f.path, getattr(f.node, "lineno", 0),
                           f.rule_id, f.message))

    # -- pass 1: mesh construction sites -----------------------------------
    def _scan_meshes(self, ctx) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(_dotted(node.func))
            if tail == "make_mesh":
                self.mesh_axes |= set(DEFAULT_MESH_AXES)
                self.mesh_sites.append((ctx.path, "make_mesh"))
                self.fact_files.add(ctx.path)
            elif tail == "Mesh":
                axes: Set[str] = set()
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes |= _str_literals(kw.value)
                if len(node.args) >= 2:
                    axes |= _str_literals(node.args[1])
                if axes:
                    self.mesh_axes |= axes
                    self.mesh_sites.append((ctx.path, "Mesh"))
                    self.fact_files.add(ctx.path)

    # -- pass 2: units ------------------------------------------------------
    def _walk_module(self, ctx) -> None:
        module = self._module_names[ctx.path]
        top = [s for s in ctx.tree.body
               if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))]
        _UnitWalk(self, ctx, module, None, "<module>").run(top)

        def visit(node, class_name):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    _UnitWalk(self, ctx, module, class_name,
                              child.name).run(child.body)
                    visit(child, class_name)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                else:
                    visit(child, class_name)

        visit(ctx.tree, None)

    # -- recording ----------------------------------------------------------
    def add_finding(self, rule_id: str, ctx, node: ast.AST, message: str,
                    subject: str) -> None:
        self.fact_files.add(ctx.path)
        self.findings.append(PlacementFinding(
            rule_id=rule_id, path=ctx.path, node=node, message=message,
            ident=f"{rule_id}|{ctx.path}|{subject}"))

    # -- engine surface ----------------------------------------------------
    def findings_for(self, path: str) -> List[PlacementFinding]:
        return [f for f in self.findings if f.path == path]

    def reach_expansion(self, changed: Set[str]) -> Set[str]:
        """Files whose placement findings a change to ``changed`` can
        alter. Placement is whole-program through two globals — the
        mesh-axes union and the partition-rule table — so the group is
        every file carrying a placement fact plus the helper/table
        files; a changed file inside the group re-reports the whole
        group, a changed file outside it expands nothing."""
        out: Set[str] = set(changed)
        known = {c.path for c in self.contexts}
        group = set(self.fact_files)
        group |= {h for h in HELPER_FILES if h in known}
        if group & changed:
            out |= group
        return out

    def digest_items(self) -> List[str]:
        """Line-number-free serialization of everything that shapes the
        placement findings, folded into the program digest: mesh-axes
        drift, rule-table edits, or any finding change invalidates
        every module's cached result; line drift stays warm."""
        items = [f"pl-axes|{','.join(sorted(self.mesh_axes))}",
                 f"pl-table|{self.table_digest}"]
        items.extend(f"pl-mesh|{p}|{form}" for p, form in self.mesh_sites)
        items.extend(f"pl-find|{f.ident}|{f.message}"
                     for f in self.findings)
        return sorted(items)


# -- the per-unit pass -------------------------------------------------------


class _UnitWalk:
    """One statement-ordered walk over one function body (or the module
    top level), tracking each local name's lattice point. ``cond``
    counts enclosing conditionals: a placement recorded at cond > 0 is
    a MAY placement and never fires."""

    def __init__(self, model: PlacementModel, ctx, module: str,
                 class_name: Optional[str], fn_name: str):
        self.model = model
        self.ctx = ctx
        self.module = module
        self.class_name = class_name
        self.fn_name = fn_name
        self.sanctioned = bool(SANCTIONED_READ_RE.search(fn_name))
        self.env: Dict[str, _Bind] = {}
        self.jits: Dict[str, _LocalJit] = {}
        self.cond = 0

    def run(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    # -- statements --------------------------------------------------------
    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate analyzable units
        if isinstance(stmt, ast.If):
            self._calls_in(stmt.test)
            self.cond += 1
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            self.cond -= 1
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._calls_in(stmt.iter if hasattr(stmt, "iter")
                           else stmt.test)
            self.cond += 1
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            self.cond -= 1
            return
        if isinstance(stmt, ast.Try):
            self.cond += 1
            for s in (stmt.body + [h for hd in stmt.handlers
                                   for h in hd.body]
                      + stmt.orelse + stmt.finalbody):
                self._stmt(s)
            self.cond -= 1
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._calls_in(item.context_expr)
            for s in stmt.body:   # `with mesh:` does not branch
                self._stmt(s)
            return
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            self._assign(stmt.targets[0].id, stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            self._assign(stmt.target.id, stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._calls_in(stmt.value, discarded=True)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._calls_in(child)

    # -- assignment --------------------------------------------------------
    def _assign(self, name: str, value: ast.AST) -> None:
        # Function-local jit wraps bind dispatch boundaries, not arrays.
        jit_call = _jit_callee(value)
        if jit_call is not None:
            lj = _LocalJit()
            for kw in jit_call.keywords:
                if kw.arg == "donate_argnums":
                    lj.donate |= _int_literals(kw.value)
                elif kw.arg in ("in_shardings", "in_axis_resources"):
                    lj.in_spec = self._spec_of(kw.value)
            self.jits[name] = lj
            self.env.pop(name, None)
            self._calls_in(value, skip=jit_call)
            return
        self._calls_in(value, rebind=name)
        self.env[name] = self._eval(value)

    def _eval(self, value: ast.AST) -> _Bind:
        definite = self.cond == 0
        if isinstance(value, ast.Name):
            hit = self.env.get(value.id)
            if hit is not None:
                return _Bind(**{**hit.__dict__})
            return _Bind()
        if not isinstance(value, ast.Call):
            return _Bind()
        tail = _tail(_dotted(value.func))
        if tail in _MESH_CTOR_TAILS:
            return _Bind(kind="mesh", definite=definite, node=value)
        if tail == "NamedSharding" and len(value.args) >= 2:
            spec = self._spec_of(value.args[1])
            return _Bind(kind="ns", spec=spec, definite=definite,
                         node=value)
        if self._is_pspec(value):
            spec, _axes, _arity = parse_spec(value)
            return _Bind(kind="spec", spec=spec, definite=definite,
                         node=value)
        placed = self._placement_of(value)
        if placed is not None:
            level, spec = placed
            return _Bind(level=level, spec=spec, definite=definite,
                         node=value)
        if tail in _HOST_CTOR_TAILS:
            return _Bind(level=HOST, rank=_ctor_rank(value),
                         definite=definite, node=value)
        if tail == "device_get":
            return _Bind(level=HOST, definite=definite, node=value)
        return _Bind()

    # -- placement recognizers ---------------------------------------------
    def _placement_of(self, call: ast.Call):
        """(level, spec) when ``call`` is a placement transfer."""
        tail = _tail(_dotted(call.func))
        if tail == "device_put":
            if len(call.args) < 2:
                return REPLICATED, None
            spec = self._sharding_spec(call.args[1])
            if spec is None:
                return SHARDED, None
            return (REPLICATED, spec) if spec == "P()" else (SHARDED, spec)
        if tail == "with_sharding_constraint" and len(call.args) >= 2:
            spec = self._sharding_spec(call.args[1])
            return (REPLICATED, spec) if spec == "P()" else (SHARDED, spec)
        if tail in _PLACE_SHARDED_TAILS:
            return SHARDED, "P('dp')" if tail == "shard_docs" else None
        if tail in _PLACE_REPLICATED_TAILS:
            return REPLICATED, "P()"
        return None

    def _sharding_spec(self, expr: ast.AST) -> Optional[str]:
        """NamedSharding(mesh, spec) / spec literal / bound name ->
        canonical spec string when known."""
        if isinstance(expr, ast.Call):
            tail = _tail(_dotted(expr.func))
            if tail == "NamedSharding" and len(expr.args) >= 2:
                return self._spec_of(expr.args[1])
            if self._is_pspec(expr):
                return parse_spec(expr)[0]
            return None
        if isinstance(expr, ast.Name):
            hit = self.env.get(expr.id)
            if hit is not None and hit.kind in ("spec", "ns"):
                return hit.spec
        return None

    def _spec_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts:
            return self._spec_of(expr.elts[0])
        return self._sharding_spec(expr)

    def _is_pspec(self, call: ast.Call) -> bool:
        dotted = _dotted(call.func)
        if _tail(dotted) == "PartitionSpec":
            return True
        return "." not in dotted and _pspec_alias_ok(
            self.model, self.module, dotted)

    # -- calls -------------------------------------------------------------
    def _calls_in(self, expr: ast.AST, discarded: bool = False,
                  rebind: Optional[str] = None,
                  skip: Optional[ast.AST] = None) -> None:
        """Process every Call in ``expr`` source order, skipping nested
        function/lambda bodies (separate units / deferred)."""
        stack = [expr]
        calls: List[ast.Call] = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)) or node is skip:
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
        calls.sort(key=lambda c: (getattr(c, "lineno", 0),
                                  getattr(c, "col_offset", 0)))
        top = expr if isinstance(expr, ast.Call) else None
        for call in calls:
            self._call(call, discarded=(discarded and call is top),
                       rebind=(rebind if call is top else None))

    def _call(self, call: ast.Call, discarded: bool = False,
              rebind: Optional[str] = None) -> None:
        dotted = _dotted(call.func)
        tail = _tail(dotted)
        # PSPEC_MISMATCH: axis names vs the program-wide mesh union.
        if self._is_pspec(call):
            _spec, axes, _arity = parse_spec(call)
            bad = sorted(axes - self.model.mesh_axes)
            if bad:
                self.model.add_finding(
                    "PSPEC_MISMATCH", self.ctx, call,
                    f"PartitionSpec names ax{'es' if len(bad) > 1 else 'is'}"
                    f" {', '.join(repr(b) for b in bad)} but every mesh "
                    f"this program builds has axes "
                    f"{sorted(self.model.mesh_axes)} — GSPMD rejects the "
                    f"spec at dispatch time; name a real mesh axis or "
                    f"extend the mesh construction",
                    subject=f"{self.fn_name}|axes:{','.join(bad)}")
            return
        # Placement transfers: drift + arity checks, env updates happen
        # at the enclosing assignment.
        placed = self._placement_of(call)
        if placed is not None:
            self._check_placement(call, tail, placed, discarded, rebind)
            return
        if tail in _PLACEMENT_TAILS or tail in _MESH_CTOR_TAILS:
            return
        # Host reads of definitely-sharded bindings.
        if self._check_host_read(call, dotted, tail):
            return
        # Dispatch boundary: donation gate + in_shardings drift +
        # unspecced pools.
        self._check_dispatch(call, tail)

    # -- rule checks -------------------------------------------------------
    def _check_placement(self, call: ast.Call, tail: str, placed,
                         discarded: bool, rebind: Optional[str]) -> None:
        level, spec = placed
        if not call.args or self.cond != 0:
            return
        target = call.args[0]
        prior = self.env.get(target.id) if isinstance(target, ast.Name) \
            else None
        # SHARD_AXIS_DRIFT: a second conflicting placement of a binding
        # that is already definitely sharded. Rebinding the SAME name is
        # the explicit reshard idiom and stays quiet; a discarded
        # constraint (with_sharding_constraint has no side effect) or a
        # conflicting copy both fire.
        if prior is not None and prior.definite and prior.level == SHARDED \
                and prior.spec is not None and spec is not None \
                and spec != prior.spec and spec != "P()" \
                and rebind != target.id:
            how = ("the constraint's result is discarded — "
                   "with_sharding_constraint is pure, this is a no-op"
                   if discarded else "no explicit reshard in between")
            self.model.add_finding(
                "SHARD_AXIS_DRIFT", self.ctx, call,
                f"`{target.id}` is already mesh-sharded as {prior.spec} "
                f"but is placed here under {spec} ({how}); reshard by "
                f"rebinding (`{target.id} = ...`) or dispatch both "
                f"consumers under one spec",
                subject=f"{self.fn_name}|{target.id}|{prior.spec}->{spec}")
        # PSPEC_MISMATCH (arity form): spec longer than the target's
        # syntactically known rank.
        if prior is not None and prior.rank is not None \
                and tail in ("device_put", "with_sharding_constraint") \
                and len(call.args) >= 2:
            arity = self._spec_arity(call.args[1])
            if arity is not None and arity > prior.rank:
                self.model.add_finding(
                    "PSPEC_MISMATCH", self.ctx, call,
                    f"PartitionSpec has {arity} entries but "
                    f"`{target.id}` has rank {prior.rank} — jax raises "
                    f"at device_put; drop the extra axes",
                    subject=f"{self.fn_name}|{target.id}|arity:{arity}")

    def _spec_arity(self, expr: ast.AST) -> Optional[int]:
        if isinstance(expr, ast.Call):
            tail = _tail(_dotted(expr.func))
            if tail == "NamedSharding" and len(expr.args) >= 2:
                return self._spec_arity(expr.args[1])
            if self._is_pspec(expr):
                return parse_spec(expr)[2]
        return None

    def _check_host_read(self, call: ast.Call, dotted: str,
                         tail: str) -> bool:
        subject: Optional[str] = None
        if isinstance(call.func, ast.Attribute) \
                and tail in _HOST_READ_METHOD_TAILS \
                and isinstance(call.func.value, ast.Name):
            subject = call.func.value.id
        elif dotted in _HOST_READ_FN_NAMES and len(call.args) == 1 \
                and isinstance(call.args[0], ast.Name):
            subject = call.args[0].id
        elif "." in dotted and dotted.split(".", 1)[0] in _NP_HEADS \
                and tail in _HOST_READ_NP_TAILS and call.args \
                and isinstance(call.args[0], ast.Name):
            subject = call.args[0].id
        if subject is None:
            return False
        bind = self.env.get(subject)
        if bind is None or bind.level != SHARDED or not bind.definite \
                or self.sanctioned:
            return False
        form = f".{tail}()" if isinstance(call.func, ast.Attribute) \
            else f"{dotted}(...)"
        self.model.add_finding(
            "HOST_READ_OF_SHARDED", self.ctx, call,
            f"`{form}` on `{subject}`, which is mesh-sharded as "
            f"{bind.spec or 'an unresolved spec'}: this gathers every "
            f"shard through a blocking host transfer on the serving "
            f"path; use a sanctioned gather helper (a *gather*/"
            f"*to_host* function) or keep the reduction on-device",
            subject=f"{self.fn_name}|{subject}|{tail}")
        return True

    def _check_dispatch(self, call: ast.Call, tail: str) -> None:
        donated: List[ast.AST] = []
        in_spec: Optional[str] = None
        callee_name = tail or "<call>"
        local = self.jits.get(_dotted(call.func)) \
            if isinstance(call.func, ast.Name) else None
        if local is not None:
            donated = [a for i, a in enumerate(call.args)
                       if i in local.donate]
            in_spec = local.in_spec
        else:
            res = self.model.index.resolve_call(
                self.module, call, class_name=self.class_name)
            if res is not None and res.donation is not None:
                donated = res.donation.donated_args(call, res.bound_self)
                callee_name = res.donation.callee or callee_name
        # MESH_DONATION_GATE: a donated argument that is DEFINITELY
        # mesh-sharded. Enforces R6 — donated dp-sharded planes reloaded
        # through the persistent compile cache corrupt on warm reload
        # (docs/serving_pipeline.md), which is why every paged pool
        # entry point keeps a non-donating twin selected at
        # construction (mergetree/paging.py).
        for arg in donated:
            if not isinstance(arg, ast.Name):
                continue
            bind = self.env.get(arg.id)
            if bind is not None and bind.level == SHARDED \
                    and bind.definite:
                self.model.add_finding(
                    "MESH_DONATION_GATE", self.ctx, call,
                    f"`{arg.id}` is mesh-sharded "
                    f"({bind.spec or 'spec unresolved'}) and donated to "
                    f"`{callee_name}`: donating mesh-placed planes "
                    f"corrupts state on warm reload through the "
                    f"persistent compile cache (R6); dispatch through "
                    f"the non-donating keep variant on meshes "
                    f"(see mergetree/paging.py)",
                    subject=f"{self.fn_name}|{arg.id}|{callee_name}")
            if bind is not None and self.cond == 0:
                bind.level = DONATED
                bind.spec = None
        # Dispatch-spec drift: the same binding crossing two jit
        # boundaries whose in_shardings disagree.
        if in_spec is not None:
            for arg in call.args:
                if not isinstance(arg, ast.Name):
                    continue
                bind = self.env.get(arg.id)
                if bind is None:
                    continue
                if bind.dispatch_spec is not None \
                        and bind.dispatch_spec != in_spec \
                        and bind.definite:
                    self.model.add_finding(
                        "SHARD_AXIS_DRIFT", self.ctx, call,
                        f"`{arg.id}` is dispatched here under "
                        f"in_shardings {in_spec} but previously crossed "
                        f"a jit boundary under {bind.dispatch_spec} "
                        f"with no explicit reshard — GSPMD inserts a "
                        f"silent full reshard every call; pick one "
                        f"spec or reshard explicitly",
                        subject=f"{self.fn_name}|{arg.id}|"
                                f"{bind.dispatch_spec}->{in_spec}")
                bind.dispatch_spec = in_spec
        # UNSPECCED_POOL: a pool-convention pytree reaching a dispatch
        # that also takes definitely-mesh-sharded input, while the pool
        # itself is still definitely host-resident — the dispatch
        # replicates the whole pool onto every device.
        if tail in _PLACEMENT_TAILS:
            return
        sharded_arg = any(
            isinstance(a, ast.Name)
            and (b := self.env.get(a.id)) is not None
            and b.level == SHARDED and b.definite
            for a in call.args)
        if not (sharded_arg or in_spec is not None or donated):
            return
        for arg in call.args:
            if not isinstance(arg, ast.Name) \
                    or not POOL_NAME_RE.search(arg.id):
                continue
            bind = self.env.get(arg.id)
            if bind is not None and bind.level == HOST and bind.definite:
                self.model.add_finding(
                    "UNSPECCED_POOL", self.ctx, call,
                    f"pool pytree `{arg.id}` reaches this mesh dispatch "
                    f"with no matching partition rule — it will be "
                    f"replicated onto every device instead of sharded; "
                    f"place it first via match_partition_rules/"
                    f"place_with_rules (mergetree/partition_rules.py)",
                    subject=f"{self.fn_name}|{arg.id}|{callee_name}")


# -- small helpers -----------------------------------------------------------


def _str_literals(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
    return out


def _int_literals(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {el.value for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, int)}
    return set()


def _ctor_rank(call: ast.Call) -> Optional[int]:
    if not call.args:
        return None
    tail = _tail(_dotted(call.func))
    if tail == "arange":
        return 1
    shape = call.args[0]
    if isinstance(shape, (ast.Tuple, ast.List)):
        return len(shape.elts)
    if isinstance(shape, ast.Constant) and isinstance(shape.value, int):
        return 1
    return None


def _module_name(path: str) -> str:
    from .callgraph import module_name_for_path
    return module_name_for_path(path)
