"""CLI: ``python -m fluidframework_tpu.analysis [paths]``.

Exit status 0 iff every finding is suppressed inline or baselined.
The last stdout line is always the one-line JSON summary.

``--changed-only`` scopes REPORTING to the files git says changed
(worktree vs HEAD, plus untracked) while the whole-program layer still
spans the package — the fast pre-commit mode (``make lint-changed``).
The fingerprint cache (``.fluidlint_cache.json``, disable with
``--no-cache``) makes warm full runs skip unchanged modules.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from .baseline import Baseline, DEFAULT_BASELINE_PATH
from .engine import REPO_ROOT, analyze_paths
from .registry import (RULES, all_rules, rules_help_text,
                       rules_markdown_table)
from .reporters import render_human, render_json

# Markers bounding the generated rule table in docs/static_analysis.md
# (--write-rule-docs rewrites the block; a test pins it against drift).
RULE_DOCS_PATH = REPO_ROOT / "docs" / "static_analysis.md"
RULE_DOCS_BEGIN = "<!-- rule-table:begin (generated; run " \
    "`python -m fluidframework_tpu.analysis --write-rule-docs`) -->"
RULE_DOCS_END = "<!-- rule-table:end -->"


def rewrite_rule_docs(path: Path = RULE_DOCS_PATH) -> str:
    """Replace the marker-bounded rule table with the registry's
    current one; returns the updated document text (written in place)."""
    text = path.read_text()
    begin = text.index(RULE_DOCS_BEGIN) + len(RULE_DOCS_BEGIN)
    end = text.index(RULE_DOCS_END)
    updated = (text[:begin] + "\n" + rules_markdown_table() + "\n"
               + text[end:])
    path.write_text(updated)
    return updated


def _git_changed_paths() -> set:
    """Repo-root-relative .py paths changed vs HEAD (staged, unstaged,
    and untracked). Raises on git failure — a broken diff must not
    silently become an empty (vacuously clean) scope."""
    out = set()
    for cmd in (["git", "diff", "--name-only", "HEAD", "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(cmd, cwd=str(REPO_ROOT),
                              capture_output=True, text=True, timeout=30)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip().endswith(".py"))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.analysis",
        description="fluidlint: JAX-kernel, concurrency & placement "
                    "analyzer",
        epilog=rules_help_text(),
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        default=[str(REPO_ROOT / "fluidframework_tpu")],
                        help="files/dirs to analyze (default: the package)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human")
    parser.add_argument("--baseline", type=Path,
                        default=DEFAULT_BASELINE_PATH,
                        help="baseline file (default: analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also list baselined findings (human format)")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="RULE_ID",
                        help="run only these rule ids (registry-listed "
                             "below)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--write-rule-docs", action="store_true",
                        help="regenerate the rule table in "
                             "docs/static_analysis.md from the registry "
                             "and exit")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only on files git sees as changed "
                             "(worktree vs HEAD + untracked); the "
                             "whole-program context still spans the "
                             "given paths")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the per-module result cache")
    parser.add_argument("--cache-file", type=Path, default=None,
                        help="cache file path (default: "
                             ".fluidlint_cache.json at the repo root)")
    parser.add_argument("--bench-json", type=Path, default=None,
                        metavar="PATH",
                        help="also write the analyzer perf record "
                             "(wall time, cache hits, counts) to PATH "
                             "for the BENCH trend")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:22s} [{r.family}] {r.summary}")
        return 0

    if args.write_rule_docs:
        try:
            rewrite_rule_docs()
        except (OSError, ValueError) as exc:
            print(f"error: could not rewrite rule docs: {exc}",
                  file=sys.stderr)
            return 2
        print(f"wrote {len(RULES)} rules to {RULE_DOCS_PATH}")
        return 0

    unknown = set(args.rule) - set(RULES)
    if unknown:
        parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))} "
                     f"(see --list-rules)")
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        # A typo'd path must never turn the hard gate into a vacuous
        # pass that still prints a healthy-looking summary line.
        parser.error(f"path(s) do not exist: {', '.join(missing)}")

    restrict = None
    if args.changed_only:
        try:
            changed = _git_changed_paths()
        except (OSError, RuntimeError, subprocess.TimeoutExpired) as exc:
            print(f"error: --changed-only could not read the git diff: "
                  f"{exc}", file=sys.stderr)
            return 2
        restrict = changed
        if not changed:
            print("--changed-only: no changed .py files; nothing to "
                  "analyze")
            print(json.dumps({"violations": 0, "baselined": 0}))
            return 0

    cache = None
    if not args.no_cache and not args.write_baseline:
        from .cache import DEFAULT_CACHE_PATH, ResultCache
        cache = ResultCache(args.cache_file or DEFAULT_CACHE_PATH)

    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    result = analyze_paths(args.paths, baseline=baseline, only=args.rule,
                           cache=cache, restrict=restrict)

    if args.write_baseline:
        prior = baseline if baseline is not None \
            else Baseline.load(args.baseline)
        current = result.violations + result.baselined
        merged = prior.updated_with(current)
        # Entries outside this run's scope (file not analyzed, or rule
        # filtered out by --rule) survive untouched — a scoped
        # --write-baseline must never discard curated acceptances; only
        # a full default run retires stale entries.
        from .engine import _rel_path, iter_python_files
        analyzed = {_rel_path(f) for f in iter_python_files(args.paths)}
        if restrict is not None:
            # --changed-only: only the restricted files actually
            # REPORTED, so only their entries may be retired — the
            # unchanged files' curated acceptances are out of scope.
            analyzed &= restrict
        active = set(args.rule) or set(RULES)
        merged.entries.extend(
            e for e in prior.entries
            if e["path"] not in analyzed or e["rule"] not in active)
        merged = Baseline(merged.entries)
        merged.save(args.baseline)
        print(f"wrote {len(merged)} entries to {args.baseline} "
              f"({len(current)} from this run)")
        return 0

    if result.files == 0:
        if restrict is not None:
            # Changed files exist, just none inside the analyzed paths:
            # a legitimately clean scoped run, not a vacuous pass.
            print("--changed-only: no changed files within the analyzed "
                  "paths")
            print(json.dumps({"violations": 0, "baselined": 0}))
            return 0
        print("error: no Python files matched the given paths; "
              "refusing to report a vacuous pass", file=sys.stderr)
        return 2

    if args.bench_json is not None:
        record = {
            "metric": "fluidlint analyzer wall time",
            "value": round(result.wall_ms, 3),
            "unit": "ms",
            "changed_only": bool(args.changed_only),
            **result.stats,
        }
        args.bench_json.write_text(json.dumps(record, indent=2) + "\n")

    if args.format == "json":
        render_json(result, sys.stdout)
    else:
        render_human(result, sys.stdout,
                     show_baselined=args.show_baselined)
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
