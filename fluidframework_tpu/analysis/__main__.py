"""CLI: ``python -m fluidframework_tpu.analysis [paths]``.

Exit status 0 iff every finding is suppressed inline or baselined.
The last stdout line is always the one-line JSON summary.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .baseline import Baseline, DEFAULT_BASELINE_PATH
from .engine import REPO_ROOT, analyze_paths
from .registry import RULES, all_rules
from .reporters import render_human, render_json


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.analysis",
        description="fluidlint: JAX-kernel & server-concurrency analyzer")
    parser.add_argument("paths", nargs="*",
                        default=[str(REPO_ROOT / "fluidframework_tpu")],
                        help="files/dirs to analyze (default: the package)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human")
    parser.add_argument("--baseline", type=Path,
                        default=DEFAULT_BASELINE_PATH,
                        help="baseline file (default: analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also list baselined findings (human format)")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="RULE_ID", help="run only these rule ids")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:22s} [{r.family}] {r.summary}")
        return 0

    unknown = set(args.rule) - set(RULES)
    if unknown:
        parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))} "
                     f"(see --list-rules)")
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        # A typo'd path must never turn the hard gate into a vacuous
        # pass that still prints a healthy-looking summary line.
        parser.error(f"path(s) do not exist: {', '.join(missing)}")

    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    result = analyze_paths(args.paths, baseline=baseline, only=args.rule)

    if args.write_baseline:
        prior = baseline if baseline is not None \
            else Baseline.load(args.baseline)
        current = result.violations + result.baselined
        merged = prior.updated_with(current)
        # Entries outside this run's scope (file not analyzed, or rule
        # filtered out by --rule) survive untouched — a scoped
        # --write-baseline must never discard curated acceptances; only
        # a full default run retires stale entries.
        from .engine import _rel_path, iter_python_files
        analyzed = {_rel_path(f) for f in iter_python_files(args.paths)}
        active = set(args.rule) or set(RULES)
        merged.entries.extend(
            e for e in prior.entries
            if e["path"] not in analyzed or e["rule"] not in active)
        merged = Baseline(merged.entries)
        merged.save(args.baseline)
        print(f"wrote {len(merged)} entries to {args.baseline} "
              f"({len(current)} from this run)")
        return 0

    if result.files == 0:
        print("error: no Python files matched the given paths; "
              "refusing to report a vacuous pass", file=sys.stderr)
        return 2

    if args.format == "json":
        render_json(result, sys.stdout)
    else:
        render_human(result, sys.stdout,
                     show_baselined=args.show_baselined)
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
