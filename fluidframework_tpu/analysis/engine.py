"""fluidlint engine: parse, shared AST context, suppressions, dispatch.

The engine owns everything rule implementations share: the parsed tree
with parent links, the jit-function index (decorator and call forms,
with ``static_argnums``/``donate_argnums`` parsed out), inline
suppression comments, and stable violation fingerprints for the
baseline. Rules stay small predicate functions over this context.
"""

from __future__ import annotations

import ast
import hashlib
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# Repo root = the directory holding the fluidframework_tpu package;
# baseline entries key file paths relative to it so the gate is stable
# regardless of the CWD the analyzer runs from.
REPO_ROOT = Path(__file__).resolve().parents[2]

SUPPRESS_RE = re.compile(
    r"#\s*fluidlint:\s*disable(?:=(?P<rules>[A-Z0-9_,\s]+))?"
    r"(?:\s*[—:-]\s*(?P<reason>.*))?")


@dataclass(frozen=True)
class Violation:
    rule_id: str
    path: str          # repo-root-relative (or absolute if outside)
    line: int
    col: int
    message: str
    symbol: str        # enclosing def/class qualname ("" at module level)
    line_text: str

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: deliberately excludes
        the line *number* (which drifts on unrelated edits) in favour of
        the enclosing symbol plus the normalized source line."""
        raw = "|".join((self.rule_id, self.path, self.symbol,
                        " ".join(self.line_text.split())))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.rule_id}{sym}: {self.message}"


@dataclass
class JitInfo:
    """How a function is jitted: which params are static (safe to branch
    on) and which are donated."""
    node: ast.FunctionDef
    static_argnums: Set[int] = field(default_factory=set)
    static_argnames: Set[str] = field(default_factory=set)
    donate_argnums: Set[int] = field(default_factory=set)
    donate_argnames: Set[str] = field(default_factory=set)
    form: str = "decorator"  # "decorator" | "call"

    def traced_params(self) -> Set[str]:
        names = set()
        for i, arg in enumerate(self.node.args.args):
            if i in self.static_argnums or arg.arg in self.static_argnames:
                continue
            names.add(arg.arg)
        return names


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_ref(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _int_elems(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
        return out
    return set()


def _str_elems(node: ast.AST) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {el.value for el in node.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)}
    return set()


def _jit_kwargs(call: ast.Call, info: JitInfo) -> None:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            info.static_argnums |= _int_elems(kw.value)
        elif kw.arg == "static_argnames":
            info.static_argnames |= _str_elems(kw.value)
        elif kw.arg == "donate_argnums":
            info.donate_argnums |= _int_elems(kw.value)
        elif kw.arg == "donate_argnames":
            info.donate_argnames |= _str_elems(kw.value)


def decorator_jit_info(node) -> Optional[JitInfo]:
    """JitInfo for a decorator-jitted def (`@jax.jit`, `@jax.jit(…)`,
    `@functools.partial(jax.jit, …)`), else None. The one recognizer
    shared by the per-module jit index and the call graph — a new jit
    spelling lands in both or neither."""
    for dec in node.decorator_list:
        if _is_jit_ref(dec):
            return JitInfo(node=node)
        if isinstance(dec, ast.Call):
            if _is_jit_ref(dec.func):
                info = JitInfo(node=node)
                _jit_kwargs(dec, info)
                return info
            if (_dotted(dec.func) in ("functools.partial", "partial")
                    and dec.args and _is_jit_ref(dec.args[0])):
                info = JitInfo(node=node)
                _jit_kwargs(dec, info)
                return info
    return None


class ModuleContext:
    """Everything rules need about one source file.

    The derived indexes (parent links, jit functions, suppressions) are
    LAZY: a fully-cached analyze_paths run parses every module for the
    whole-program layer but never runs a rule against most of them, and
    building the parent map for 160 modules dominates warm wall time."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._jit_functions: Optional[Dict[ast.FunctionDef, JitInfo]] = None
        self._suppressions: Optional[Dict[int, Set[str]]] = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    @property
    def jit_functions(self) -> Dict[ast.FunctionDef, JitInfo]:
        if self._jit_functions is None:
            self._jit_functions = {}
            self._index_jit_functions()
        return self._jit_functions

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        if self._suppressions is None:
            self._suppressions = self._scan_suppressions()
        return self._suppressions

    # -- jit detection -----------------------------------------------------
    def _index_jit_functions(self) -> None:
        by_name: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                by_name.setdefault(node.name, []).append(node)
                info = self._decorator_jit_info(node)
                if info is not None:
                    self.jit_functions[node] = info
        # Call form: jax.jit(fn, ...) where fn is a Name that resolves to
        # exactly one FunctionDef in this module.
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and _is_jit_ref(node.func)
                    and node.args):
                continue
            target = node.args[0]
            if not isinstance(target, ast.Name):
                continue
            defs = by_name.get(target.id, [])
            if len(defs) != 1 or defs[0] in self.jit_functions:
                continue
            info = JitInfo(node=defs[0], form="call")
            _jit_kwargs(node, info)
            self.jit_functions[defs[0]] = info

    def _decorator_jit_info(self,
                            node: ast.FunctionDef) -> Optional[JitInfo]:
        return decorator_jit_info(node)

    def enclosing_jit(self, node: ast.AST) -> Optional[JitInfo]:
        """The jit-decorated function lexically containing ``node``, if
        any — nested helper defs inside a jitted body count (they trace
        when the jitted caller runs them)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, ast.FunctionDef) and cur in self.jit_functions:
                return self.jit_functions[cur]
            cur = self.parents.get(cur)
        return None

    def symbol_for(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    # -- suppressions ------------------------------------------------------
    def _scan_suppressions(self) -> Dict[int, Set[str]]:
        """line -> rule ids disabled there ({"all"} disables everything).
        A suppression comment applies to its own line; a standalone
        comment line applies to the next line as well (so long
        statements can carry the comment just above them)."""
        out: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = m.group("rules")
            ids = ({r.strip() for r in rules.split(",") if r.strip()}
                   if rules else {"all"})
            line = tok.start[0]
            out.setdefault(line, set()).update(ids)
            stripped = self.lines[line - 1].strip() if \
                line <= len(self.lines) else ""
            if stripped.startswith("#"):
                # Standalone comment: applies to the next code line, even
                # across the rest of the comment block and blank lines.
                nxt = line + 1
                while nxt <= len(self.lines) and (
                        not self.lines[nxt - 1].strip()
                        or self.lines[nxt - 1].strip().startswith("#")):
                    nxt += 1
                out.setdefault(nxt, set()).update(ids)
        return out

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        ids = self.suppressions.get(line, set())
        return "all" in ids or rule_id in ids

    # -- violation helper --------------------------------------------------
    def violation(self, rule_id: str, node: ast.AST,
                  message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        return Violation(rule_id=rule_id, path=self.path, line=line,
                         col=col, message=message,
                         symbol=self.symbol_for(node),
                         line_text=text.strip())


@dataclass
class AnalysisResult:
    violations: List[Violation]          # new (not suppressed/baselined)
    baselined: List[Violation]
    suppressed: int
    files: int
    wall_ms: float = 0.0                 # analyzer wall time, this run
    cache_hits: int = 0                  # modules served from the cache
    cache_misses: int = 0                # modules actually re-analyzed
    race_rules_wall_ms: float = 0.0      # lockset model build + findings
    placement_rules_wall_ms: float = 0.0  # placement model build + findings

    @property
    def summary(self) -> dict:
        return {"violations": len(self.violations),
                "baselined": len(self.baselined)}

    @property
    def stats(self) -> dict:
        """The perf/trend block stamped into JSON reports and the
        BENCH_LINT record (wall time + cache effectiveness + counts)."""
        return {"wall_ms": round(self.wall_ms, 3), "files": self.files,
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "race_rules_wall_ms": round(self.race_rules_wall_ms, 3),
                "placement_rules_wall_ms":
                    round(self.placement_rules_wall_ms, 3),
                "suppressed": self.suppressed, **self.summary}


class ProgramContext:
    """Whole-program layer shared by the cross-module rules: the
    symbol/call graph (callgraph.ProgramIndex) plus the per-function
    dataflow summaries (dataflow.compute_summaries). Built once per
    analyze_paths run and attached to every ModuleContext as
    ``ctx.program``; analyze_source builds a single-module one on
    demand (lifecycle_rules._program_for)."""

    def __init__(self, contexts: Sequence["ModuleContext"]):
        from .callgraph import ProgramIndex, module_name_for_path
        self.contexts = list(contexts)
        self.index = ProgramIndex(
            [(module_name_for_path(c.path), c.tree, c.path)
             for c in contexts])
        from .dataflow import compute_summaries
        self.summaries = compute_summaries(self.index)
        self._concurrency = None
        self.race_wall_ms = 0.0
        self._placement = None
        self.placement_wall_ms = 0.0

    def concurrency(self):
        """The whole-program lockset model (concurrency_model.py),
        built lazily once per run and shared by the race rules, the
        --changed-only reach expansion, and the cache digest. Build
        time accumulates into ``race_wall_ms`` (stamped into the
        BENCH_LINT record as ``race_rules_wall_ms``)."""
        if self._concurrency is None:
            import time
            t0 = time.perf_counter()
            from .concurrency_model import ConcurrencyModel
            self._concurrency = ConcurrencyModel(self.index,
                                                 self.contexts)
            self.race_wall_ms += (time.perf_counter() - t0) * 1000.0
        return self._concurrency

    def placement(self):
        """The whole-program placement lattice (placement_model.py),
        built lazily once per run and shared by the placement rules,
        the --changed-only reach expansion, and the cache digest. Build
        time accumulates into ``placement_wall_ms`` (stamped into the
        BENCH_LINT record as ``placement_rules_wall_ms``)."""
        if self._placement is None:
            import time
            t0 = time.perf_counter()
            from .placement_model import PlacementModel
            self._placement = PlacementModel(self.index, self.contexts)
            self.placement_wall_ms += (time.perf_counter() - t0) * 1000.0
        return self._placement

    def digest(self, include_concurrency: bool = True,
               include_placement: bool = True) -> str:
        """Interface digest for the result cache: any change to a
        donation signature, transitive summary, concurrency fact
        (lock decl, thread root, race finding), or placement fact
        (mesh axes, partition-rule table, placement finding) anywhere
        invalidates every module's cached result (a caller two modules
        away may now be donating — or racing, or resharding — where it
        wasn't). ``include_concurrency=False`` / ``include_placement=
        False`` skip the family's model facts for runs whose rule
        filter excludes it — their cached results contain no findings
        of that family, so its drift is irrelevant to them (the rule
        filter is part of the cache key), and skipping avoids both the
        model-build cost and spurious invalidation."""
        items = list(self.index.signature_digest_items())
        for q in sorted(self.summaries):
            s = self.summaries[q]
            if s.donated_params or s.metadata_only_params:
                items.append(f"{q}|{sorted(s.donated_params)}|"
                             f"{sorted(s.metadata_only_params)}")
        if include_concurrency:
            items.extend(self.concurrency().digest_items())
        if include_placement:
            items.extend(self.placement().digest_items())
        return hashlib.sha1("\n".join(items).encode()).hexdigest()[:20]


def _rel_path(path: Path) -> str:
    path = path.resolve()
    try:
        return path.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def iter_python_files(paths: Sequence[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" not in f.parts:
                    yield f
        elif p.suffix == ".py":
            yield p


def analyze_source(source: str, path: str = "<memory>",
                   only: Iterable[str] = ()) -> List[Violation]:
    """Run (a subset of) the rules over one source string. Suppressions
    apply; baseline does not (that is a CLI-level concern). Fixture
    tests drive this directly."""
    from .registry import iter_checks
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Violation(rule_id="PARSE_ERROR", path=path,
                          line=exc.lineno or 1, col=exc.offset or 0,
                          message=f"could not parse: {exc.msg}",
                          symbol="", line_text="")]
    ctx = ModuleContext(path, source, tree)
    out: List[Violation] = []
    for r in iter_checks(only):
        for v in r.check(ctx):
            if not ctx.is_suppressed(v.rule_id, v.line):
                out.append(v)
    out.sort(key=lambda v: (v.line, v.col, v.rule_id))
    return out


def analyze_paths(paths: Sequence[str], baseline=None,
                  only: Iterable[str] = (), cache=None,
                  restrict: Optional[Set[str]] = None) -> AnalysisResult:
    """Analyze ``paths``. ``cache`` (analysis.cache.ResultCache) skips
    modules whose (source, rules, program-interface) fingerprints are
    unchanged. ``restrict`` limits REPORTING to the given repo-relative
    paths while the whole-program context still spans everything parsed
    — the ``--changed-only`` pre-commit mode."""
    import time
    t0 = time.perf_counter()
    from .registry import iter_checks
    rules = iter_checks(only)
    new: List[Violation] = []
    base: List[Violation] = []
    suppressed = 0
    files = 0
    contexts: List[ModuleContext] = []
    sources: Dict[str, str] = {}
    for file in iter_python_files(paths):
        rel = _rel_path(file)
        try:
            source = file.read_text()
            tree = ast.parse(source)
        except (OSError, SyntaxError, UnicodeDecodeError) as exc:
            if restrict is None or rel in restrict:
                files += 1
                new.append(Violation(
                    rule_id="PARSE_ERROR", path=rel, line=1, col=0,
                    message=f"could not parse: {exc}", symbol="",
                    line_text=""))
            continue
        contexts.append(ModuleContext(rel, source, tree))
        sources[rel] = source
    # The whole-program layer spans every parsed module, restricted or
    # not: a donation signature lives wherever it lives.
    program = ProgramContext(contexts)
    race_active = any(r.family == "race" for r in rules)
    placement_active = any(r.family == "placement" for r in rules)
    # The digest (and the family-model builds inside it) is a cache
    # concern: a cacheless run pays a model only if a rule of that
    # family actually checks a module in scope.
    program_dig = "" if cache is None else \
        program.digest(include_concurrency=race_active,
                       include_placement=placement_active)
    rules_dig = ""
    if cache is not None:
        from .cache import rules_digest
        rules_dig = rules_digest()
    only_key = tuple(sorted(only))
    # Storage slot per (module, rule filter): a focused run (make
    # lint-races) and the full run (make lint-analysis) share the cache
    # file without evicting each other's entries.
    slot_suffix = ("#" + ",".join(only_key)) if only_key else ""
    # Race and placement findings are whole-program: a change to any
    # file in a thread root's reach (or a placement group — mesh axes
    # and the partition-rule table span modules) can alter that
    # family's findings in OTHER files, so --changed-only additionally
    # re-reports the family's rules on every file its model's reach
    # expansion ties to a changed file.
    extra_rules: Dict[str, List] = {}
    if restrict is not None:
        for family, model_of in (
                ("race", lambda: program.concurrency()),
                ("placement", lambda: program.placement())):
            family_rules = [r for r in rules if r.family == family]
            if not family_rules:
                continue
            for path in model_of().reach_expansion(set(restrict)) \
                    - set(restrict):
                extra_rules.setdefault(path, []).extend(family_rules)
    def split_baseline(module_violations):
        for v in module_violations:
            if baseline is not None and baseline.contains(v):
                base.append(v)
            else:
                new.append(v)

    def run_rules(ctx, active_rules):
        module_violations = []
        module_suppressed = 0
        for r in active_rules:
            for v in r.check(ctx):
                if ctx.is_suppressed(v.rule_id, v.line):
                    module_suppressed += 1
                else:
                    module_violations.append(v)
        return module_violations, module_suppressed

    for ctx in contexts:
        ctx.program = program
        if restrict is not None and ctx.path not in restrict:
            if ctx.path in extra_rules:
                files += 1
                module_violations, module_suppressed = \
                    run_rules(ctx, extra_rules[ctx.path])
                suppressed += module_suppressed
                split_baseline(module_violations)
            continue
        files += 1
        cache_key = None
        if cache is not None:
            cache_key = cache.key(sources[ctx.path], rules_dig,
                                  program_dig, only_key)
            hit = cache.get(ctx.path + slot_suffix, cache_key)
            if hit is not None:
                module_violations, module_suppressed = hit
                suppressed += module_suppressed
                split_baseline(module_violations)
                continue
        module_violations, module_suppressed = run_rules(ctx, rules)
        if cache is not None:
            cache.put(ctx.path + slot_suffix, cache_key,
                      module_violations, module_suppressed)
        suppressed += module_suppressed
        split_baseline(module_violations)
    if cache is not None:
        cache.save()
    key = lambda v: (v.path, v.line, v.col, v.rule_id)  # noqa: E731
    new.sort(key=key)
    base.sort(key=key)
    return AnalysisResult(
        violations=new, baselined=base, suppressed=suppressed,
        files=files, wall_ms=(time.perf_counter() - t0) * 1000.0,
        cache_hits=cache.hits if cache is not None else 0,
        cache_misses=cache.misses if cache is not None else 0,
        race_rules_wall_ms=program.race_wall_ms,
        placement_rules_wall_ms=program.placement_wall_ms)
