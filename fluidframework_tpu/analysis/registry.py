"""Rule registry: rules self-register via the @rule decorator.

A rule is a callable ``check(ctx) -> Iterator[Violation]`` plus metadata
(id, family, rationale) used by ``--list-rules`` and the docs. Keeping
registration declarative means the engine, the CLI, and the fixture
tests all iterate the same collection — adding a rule is one decorated
function in jax_rules.py / concurrency_rules.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .engine import ModuleContext, Violation


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    family: str  # "jax" | "concurrency" | "race"
    rationale: str
    check: Callable[["ModuleContext"], Iterator["Violation"]] = field(
        repr=False, compare=False, default=None)  # type: ignore[assignment]


RULES: Dict[str, Rule] = {}


def rule(id: str, summary: str, family: str, rationale: str):
    """Register ``check(ctx)`` under a stable rule id."""

    def register(check):
        if id in RULES:
            raise ValueError(f"duplicate rule id {id!r}")
        RULES[id] = Rule(id=id, summary=summary, family=family,
                         rationale=rationale, check=check)
        return check

    return register


def all_rules() -> List[Rule]:
    return [RULES[k] for k in sorted(RULES)]


def get_rule(rule_id: str) -> Rule:
    return RULES[rule_id]


def rules_help_text() -> str:
    """The rule list for the CLI epilog — generated from the registry
    so ``--rule`` help can never drift from the registered rules."""
    lines = ["rule ids (pass to --rule; see docs/static_analysis.md):"]
    lines.extend(f"  {r.id:24s} [{r.family}] {r.summary}"
                 for r in all_rules())
    return "\n".join(lines)


def rules_markdown_table() -> str:
    """The docs rule table — the generated block in
    docs/static_analysis.md (``--write-rule-docs`` rewrites it, a test
    pins it against drift)."""
    lines = ["| Rule | Family | Summary |", "| --- | --- | --- |"]
    lines.extend(f"| `{r.id}` | {r.family} | {r.summary} |"
                 for r in all_rules())
    return "\n".join(lines)


def iter_checks(only: Iterable[str] = ()) -> List[Rule]:
    wanted = set(only)
    rules = all_rules()
    if wanted:
        unknown = wanted - set(RULES)
        if unknown:
            raise KeyError(f"unknown rule ids: {sorted(unknown)}")
        rules = [r for r in rules if r.id in wanted]
    return rules
