"""Fingerprint-keyed per-module result cache for incremental runs.

A full ``make lint-analysis`` re-analyzes ~160 modules; on an
incremental run almost none of them changed. Each module's raw rule
output (pre-baseline — the baseline split is a report-time concern) is
cached under a key that folds in everything that could change it:

* the module's own source digest,
* a digest of the analyzer's OWN sources (rule edits invalidate all),
* the whole-program interface digest (donation signatures + dataflow
  summaries, engine.ProgramContext.digest) — so editing
  ``serve_step.py``'s donate_argnums re-analyzes ``tpu_sequencer.py``
  even though that file's bytes never changed,
* the active rule filter.

Storage slots key by module path plus the active rule filter, so a
focused run (``make lint-races``) and the full run share the file
without evicting each other. The cache lives in
``.fluidlint_cache.json`` at the repo root
(gitignored); a corrupt or version-skewed file is silently discarded —
the cache can only ever cost a re-analysis, never a wrong answer.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .engine import REPO_ROOT, Violation

DEFAULT_CACHE_PATH = REPO_ROOT / ".fluidlint_cache.json"
_CACHE_VERSION = 1

_V_FIELDS = ("rule_id", "path", "line", "col", "message", "symbol",
             "line_text")


def _digest(*parts: str) -> str:
    h = hashlib.sha1()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()[:20]


def rules_digest() -> str:
    """Digest of the analyzer's own sources: editing any rule or the
    engine invalidates every cached module result."""
    here = Path(__file__).resolve().parent
    parts = []
    for f in sorted(here.glob("*.py")):
        try:
            parts.append(f.read_text())
        except OSError:
            parts.append(f.name)
    return _digest(*parts)


class ResultCache:
    def __init__(self, path: Path = DEFAULT_CACHE_PATH):
        self.path = Path(path)
        self.modules: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._load()

    def _load(self) -> None:
        try:
            data = json.loads(self.path.read_text())
            if data.get("version") == _CACHE_VERSION:
                self.modules = data.get("modules", {})
        except (OSError, ValueError):
            self.modules = {}

    def key(self, source: str, rules_dig: str, program_dig: str,
            only: Tuple[str, ...]) -> str:
        return _digest(source, rules_dig, program_dig, ",".join(only))

    def get(self, path: str, key: str
            ) -> Optional[Tuple[List[Violation], int]]:
        entry = self.modules.get(path)
        if entry is None or entry.get("key") != key:
            self.misses += 1
            return None
        self.hits += 1
        violations = [Violation(**{f: v[f] for f in _V_FIELDS})
                      for v in entry["violations"]]
        return violations, int(entry.get("suppressed", 0))

    def put(self, path: str, key: str, violations: List[Violation],
            suppressed: int) -> None:
        self.modules[path] = {
            "key": key,
            "suppressed": suppressed,
            "violations": [{f: getattr(v, f) for f in _V_FIELDS}
                           for v in violations],
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"version": _CACHE_VERSION, "modules": self.modules}
        try:
            fd, tmp = tempfile.mkstemp(
                dir=str(self.path.parent), prefix=".fluidlint_cache.")
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)  # atomic: parallel runs can race
        except OSError:
            pass  # cache is best-effort; next run simply re-analyzes
