"""CC* rules: server concurrency and robustness.

These run over the whole tree but are written against the failure modes
of the server/ and loader/ pipelines: a swallowed exception in a lambda
op path loses ops silently, a blocking call in async code stalls every
document sharing the loop, and a listener registered without a removal
path pins a document's worth of state for the process lifetime.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .engine import ModuleContext, Violation, _dotted
from .registry import rule


def _lockish(expr: ast.AST) -> bool:
    """Does this context-manager expression look like a lock? Matches
    bare names/attributes containing 'lock'/'mutex'/'sem' and calls on
    them (e.g. ``self._lock``, ``lock.acquire()``)."""
    target = expr
    if isinstance(target, ast.Call):
        target = target.func
    dotted = _dotted(target).lower()
    last = dotted.rsplit(".", 1)[-1].lstrip("_")
    return any(tok in last for tok in ("lock", "mutex", "semaphore"))


@rule("AWAIT_IN_LOCK",
      "await while holding a lock",
      family="concurrency",
      rationale="Awaiting under a held lock serializes every coroutine "
                "behind the slowest holder — and deadlocks outright when "
                "the awaited task needs the same lock. Narrow the critical "
                "section to the state mutation; await outside it.")
def await_in_lock(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_lockish(item.context_expr) for item in node.items):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                yield ctx.violation(
                    "AWAIT_IN_LOCK", sub,
                    "await while holding a lock: the lock is held across "
                    "the suspension point")


_BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the event loop; use asyncio.sleep",
    "open": "sync file IO blocks the event loop; read via a thread "
            "(asyncio.to_thread) or an async file API",
    "subprocess.run": "subprocess.run blocks the event loop; use "
                      "asyncio.create_subprocess_exec",
    "subprocess.check_output": "blocking subprocess call in async code",
    "subprocess.call": "blocking subprocess call in async code",
    "socket.create_connection": "blocking connect in async code",
}


@rule("BLOCKING_IN_ASYNC",
      "Blocking call (time.sleep / sync IO / subprocess) inside async def",
      family="concurrency",
      rationale="One blocking call inside a coroutine stalls the whole "
                "event loop — every other document's pipeline stops "
                "making progress until it returns.")
def blocking_in_async(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = _dotted(sub.func)
                if fn in _BLOCKING_CALLS:
                    yield ctx.violation(
                        "BLOCKING_IN_ASYNC", sub,
                        f"`{fn}` inside `async def {node.name}`: "
                        f"{_BLOCKING_CALLS[fn]}")


_BROAD = ("Exception", "BaseException")


def _broad_types(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:  # bare except
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_broad_types(el) for el in type_node.elts)
    return _dotted(type_node).rsplit(".", 1)[-1] in _BROAD


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """Silent = no raise, no call, and the bound exception (if any) is
    never read — nothing observes the failure."""
    for stmt in handler.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.Raise, ast.Call)):
                return False
            if (handler.name and isinstance(sub, ast.Name)
                    and sub.id == handler.name
                    and isinstance(sub.ctx, ast.Load)):
                return False
    return True


@rule("SWALLOWED_EXCEPTION",
      "Broad except (bare / Exception / BaseException) that silently "
      "drops the error",
      family="concurrency",
      rationale="On an op-pipeline path a silent drop loses ops with no "
                "forensic trail (the class of bug behind the alfred/"
                "historian route-reply handlers). Narrow the type, or at "
                "minimum count the swallow via telemetry.counters so "
                "/healthz exposes the rate.")
def swallowed_exception(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_types(node.type):
            continue
        if not _handler_is_silent(node):
            continue
        shown = "bare except" if node.type is None else \
            f"except {ast.unparse(node.type)}"
        yield ctx.violation(
            "SWALLOWED_EXCEPTION", node,
            f"`{shown}` swallows the error with no raise, log, or "
            f"counter; narrow the type or record the swallow "
            f"(telemetry.counters.record_swallow)")


_REGISTER_NAMES = {"on", "subscribe", "add_listener", "add_handler",
                   "register_listener"}
_REMOVE_NAMES = {"off", "unsubscribe", "remove_listener", "remove_handler",
                 "unregister_listener", "remove_all_listeners", "dispose"}


@rule("LISTENER_LEAK",
      "Class registers event listeners but offers no removal path",
      family="concurrency",
      rationale="A subscribe/on API without unsubscribe/off pins every "
                "registered closure (and whatever document state it "
                "captures) for the lifetime of the emitter — the "
                "long-lived-server leak class.")
def listener_leak(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {m.name: m for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        registers = [m for name, m in methods.items()
                     if name in _REGISTER_NAMES]
        if not registers:
            continue
        if any(name in _REMOVE_NAMES for name in methods):
            continue
        for m in registers:
            yield ctx.violation(
                "LISTENER_LEAK", m,
                f"`{node.name}.{m.name}` registers listeners but "
                f"`{node.name}` has no removal path "
                f"({'/'.join(sorted(_REMOVE_NAMES))})")


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call)
            and _dotted(node.func) in ("list", "dict", "set", "bytearray"))


@rule("MUTABLE_DEFAULT",
      "Mutable default argument",
      family="concurrency",
      rationale="Default values evaluate once at def time; a mutable one "
                "is shared across every call and every thread — state "
                "leaks between requests.")
def mutable_default(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        defaults: List[ast.AST] = list(args.defaults) + [
            d for d in args.kw_defaults if d is not None]
        for d in defaults:
            if _mutable_default(d):
                yield ctx.violation(
                    "MUTABLE_DEFAULT", d,
                    f"mutable default argument in `{node.name}`; use "
                    f"None and create inside the body")
