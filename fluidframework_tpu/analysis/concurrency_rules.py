"""CC* rules: server concurrency and robustness.

These run over the whole tree but are written against the failure modes
of the server/ and loader/ pipelines: a swallowed exception in a lambda
op path loses ops silently, a blocking call in async code stalls every
document sharing the loop, and a listener registered without a removal
path pins a document's worth of state for the process lifetime.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .engine import ModuleContext, Violation, _dotted
from .registry import rule


def _lockish(expr: ast.AST) -> bool:
    """Does this context-manager expression look like a lock? Matches
    bare names/attributes containing 'lock'/'mutex'/'sem' and calls on
    them (e.g. ``self._lock``, ``lock.acquire()``)."""
    target = expr
    if isinstance(target, ast.Call):
        target = target.func
    dotted = _dotted(target).lower()
    last = dotted.rsplit(".", 1)[-1].lstrip("_")
    return any(tok in last for tok in ("lock", "mutex", "semaphore"))


@rule("AWAIT_IN_LOCK",
      "await while holding a lock",
      family="concurrency",
      rationale="Awaiting under a held lock serializes every coroutine "
                "behind the slowest holder — and deadlocks outright when "
                "the awaited task needs the same lock. Narrow the critical "
                "section to the state mutation; await outside it.")
def await_in_lock(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_lockish(item.context_expr) for item in node.items):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                yield ctx.violation(
                    "AWAIT_IN_LOCK", sub,
                    "await while holding a lock: the lock is held across "
                    "the suspension point")


_BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the event loop; use asyncio.sleep",
    "open": "sync file IO blocks the event loop; read via a thread "
            "(asyncio.to_thread) or an async file API",
    "subprocess.run": "subprocess.run blocks the event loop; use "
                      "asyncio.create_subprocess_exec",
    "subprocess.check_output": "blocking subprocess call in async code",
    "subprocess.call": "blocking subprocess call in async code",
    "socket.create_connection": "blocking connect in async code",
}


@rule("BLOCKING_IN_ASYNC",
      "Blocking call (time.sleep / sync IO / subprocess) inside async def",
      family="concurrency",
      rationale="One blocking call inside a coroutine stalls the whole "
                "event loop — every other document's pipeline stops "
                "making progress until it returns.")
def blocking_in_async(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = _dotted(sub.func)
                if fn in _BLOCKING_CALLS:
                    yield ctx.violation(
                        "BLOCKING_IN_ASYNC", sub,
                        f"`{fn}` inside `async def {node.name}`: "
                        f"{_BLOCKING_CALLS[fn]}")


_BROAD = ("Exception", "BaseException")


def _broad_types(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:  # bare except
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_broad_types(el) for el in type_node.elts)
    return _dotted(type_node).rsplit(".", 1)[-1] in _BROAD


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """Silent = no raise, no call, and the bound exception (if any) is
    never read — nothing observes the failure."""
    for stmt in handler.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.Raise, ast.Call)):
                return False
            if (handler.name and isinstance(sub, ast.Name)
                    and sub.id == handler.name
                    and isinstance(sub.ctx, ast.Load)):
                return False
    return True


@rule("SWALLOWED_EXCEPTION",
      "Broad except (bare / Exception / BaseException) that silently "
      "drops the error",
      family="concurrency",
      rationale="On an op-pipeline path a silent drop loses ops with no "
                "forensic trail (the class of bug behind the alfred/"
                "historian route-reply handlers). Narrow the type, or at "
                "minimum count the swallow via telemetry.counters so "
                "/healthz exposes the rate.")
def swallowed_exception(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_types(node.type):
            continue
        if not _handler_is_silent(node):
            continue
        shown = "bare except" if node.type is None else \
            f"except {ast.unparse(node.type)}"
        yield ctx.violation(
            "SWALLOWED_EXCEPTION", node,
            f"`{shown}` swallows the error with no raise, log, or "
            f"counter; narrow the type or record the swallow "
            f"(telemetry.counters.record_swallow)")


_REGISTER_NAMES = {"on", "subscribe", "add_listener", "add_handler",
                   "register_listener"}
_REMOVE_NAMES = {"off", "unsubscribe", "remove_listener", "remove_handler",
                 "unregister_listener", "remove_all_listeners", "dispose"}


@rule("LISTENER_LEAK",
      "Class registers event listeners but offers no removal path",
      family="concurrency",
      rationale="A subscribe/on API without unsubscribe/off pins every "
                "registered closure (and whatever document state it "
                "captures) for the lifetime of the emitter — the "
                "long-lived-server leak class.")
def listener_leak(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {m.name: m for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        registers = [m for name, m in methods.items()
                     if name in _REGISTER_NAMES]
        if not registers:
            continue
        if any(name in _REMOVE_NAMES for name in methods):
            continue
        for m in registers:
            yield ctx.violation(
                "LISTENER_LEAK", m,
                f"`{node.name}.{m.name}` registers listeners but "
                f"`{node.name}` has no removal path "
                f"({'/'.join(sorted(_REMOVE_NAMES))})")


_SPAN_PRODUCERS = {"span", "start_span"}

# Span discipline is enforced where spans matter operationally: the op
# pipeline (client engine, drivers, server stages, telemetry itself).
# The server prefix deliberately covers the WHOLE tier — including the
# read path (server/readpath.py), the lambdas (broadcaster shard
# workers), and the paged rescue path in tpu_sequencer.py, which all
# carry spans as of the observability catch-up (docs/observability.md
# v2) — so a span added anywhere on the serving tier is born under the
# leak rule. "<memory>" keeps the fixture tests in scope.
_SPAN_SCOPE_PREFIXES = (
    "fluidframework_tpu/mergetree", "fluidframework_tpu/loader",
    "fluidframework_tpu/server", "fluidframework_tpu/telemetry",
    "<memory>")


def _span_scope(ctx: ModuleContext) -> bool:
    path = ctx.path.replace("\\", "/")
    return any(path.startswith(p) or f"/{p}" in path
               for p in _SPAN_SCOPE_PREFIXES)


def _enclosing_scope(ctx: ModuleContext, node: ast.AST) -> ast.AST:
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
        cur = ctx.parents.get(cur)
    return cur if cur is not None else ctx.tree


def _span_end_calls(scope: ast.AST, name: str):
    for sub in ast.walk(scope):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("end", "cancel")
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == name):
            yield sub


def _assign_block(ctx: ModuleContext, assign: ast.AST):
    """The statement list the assignment sits in, plus its index."""
    owner = ctx.parents.get(assign)
    if owner is None:
        return None, -1
    for field in ("body", "orelse", "finalbody"):
        block = getattr(owner, field, None)
        if isinstance(block, list) and assign in block:
            return block, block.index(assign)
    return None, -1


def _covered_by_finally(ctx: ModuleContext, scope: ast.AST,
                        assign: ast.AST, call: ast.Call) -> bool:
    """True when `call` (an end/cancel) sits in a Try's finalbody AND
    that Try actually protects the code after the span start: the start
    is inside the try body, or the Try is the statement IMMEDIATELY
    after the start in the same block. A finally elsewhere in the
    function proves nothing — an exception raised between the start and
    that try still leaks the span."""
    block, idx = _assign_block(ctx, assign)
    for t in ast.walk(scope):
        if not isinstance(t, ast.Try) or not t.finalbody:
            continue
        if not any(sub is call for stmt in t.finalbody
                   for sub in ast.walk(stmt)):
            continue
        if any(sub is assign for stmt in t.body
               for sub in ast.walk(stmt)):
            return True
        if block is not None and idx + 1 < len(block) \
                and block[idx + 1] is t:
            return True
    return False


@rule("SPAN_LEAK",
      "Span started without context-manager or try/finally end() "
      "protection",
      family="concurrency",
      rationale="A span whose end() sits in straight-line code never "
                "closes when anything between start and end raises — the "
                "trace shows a hole exactly where the failure happened, "
                "and an unsampled-slow span (the always-sample-on-slow "
                "policy's quarry) is lost entirely. Use `with "
                "tracing.span(...)` or end in a finally block.")
def span_leak(ctx: ModuleContext) -> Iterator[Violation]:
    if not _span_scope(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        if _dotted(call.func).rsplit(".", 1)[-1] not in _SPAN_PRODUCERS:
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not names:
            continue
        name = names[0]
        scope = _enclosing_scope(ctx, node)
        ends = list(_span_end_calls(scope, name))
        if not ends:
            yield ctx.violation(
                "SPAN_LEAK", node,
                f"span `{name}` is started but never end()ed in this "
                f"scope; use `with` or end it in a finally block")
        elif not any(_covered_by_finally(ctx, scope, node, e)
                     for e in ends):
            yield ctx.violation(
                "SPAN_LEAK", node,
                f"span `{name}` can exit without end(): no finally "
                f"block that COVERS the span start ends it — an "
                f"exception between start and end leaks the span; use "
                f"`with` or a try/finally around the started region")


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call)
            and _dotted(node.func) in ("list", "dict", "set", "bytearray"))


@rule("MUTABLE_DEFAULT",
      "Mutable default argument",
      family="concurrency",
      rationale="Default values evaluate once at def time; a mutable one "
                "is shared across every call and every thread — state "
                "leaks between requests.")
def mutable_default(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        defaults: List[ast.AST] = list(args.defaults) + [
            d for d in args.kw_defaults if d is not None]
        for d in defaults:
            if _mutable_default(d):
                yield ctx.violation(
                    "MUTABLE_DEFAULT", d,
                    f"mutable default argument in `{node.name}`; use "
                    f"None and create inside the body")


# Queue discipline is enforced where unbounded growth turns overload
# into OOM: the server pipeline. "<memory>" keeps fixture tests in
# scope. (Client-side pending queues are the DRIVER's flow-control
# problem and resubmit on reconnect; flagging them would be noise.)
_QUEUE_SCOPE_PREFIXES = ("fluidframework_tpu/server", "<memory>")

# Attribute names that read as ingest/backlog containers. Deliberately
# narrow: the rule's contract is "a thing named like a queue must show
# its bound", not "every list is suspect".
_QUEUE_NAME_TOKENS = ("queue", "backlog", "pending", "inbox", "mailbox",
                      "held", "unacked", "buffer")

_GROWTH_METHODS = {"append", "appendleft", "extend", "extendleft",
                   "insert"}


def _queue_scope(ctx: ModuleContext) -> bool:
    path = ctx.path.replace("\\", "/")
    return any(path.startswith(p) or f"/{p}" in path
               for p in _QUEUE_SCOPE_PREFIXES)


def _queueish(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _QUEUE_NAME_TOKENS)


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.NAME` -> NAME (plain attribute on self only)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _unbounded_container_init(value: ast.AST) -> bool:
    """[] / list() / dict-of-lists factories aside, a deque() WITHOUT
    maxlen. A deque(maxlen=...) is the bounded idiom and never fires."""
    if isinstance(value, ast.List):
        return True
    if isinstance(value, ast.Call):
        fn = _dotted(value.func).rsplit(".", 1)[-1]
        if fn == "list" and not value.args:
            return True
        if fn == "deque":
            return not any(kw.arg == "maxlen" for kw in value.keywords)
    return False


def _bound_evidence(cls: ast.ClassDef, attr: str) -> bool:
    """Anything in the class that reads as a bound on self.<attr>:
    a len(self.<attr>) comparison (the admission/limit-check idiom), a
    slicing trim (`self.x = self.x[-n:]` / `del self.x[:n]`), or a
    `.clear()` (swap-and-drain pattern)."""
    for node in ast.walk(cls):
        if isinstance(node, ast.Compare):
            for side in [node.left] + list(node.comparators):
                if (isinstance(side, ast.Call)
                        and _dotted(side.func) == "len" and side.args
                        and _self_attr(side.args[0]) == attr):
                    return True
        if isinstance(node, ast.Assign):
            if (any(_self_attr(t) == attr for t in node.targets)
                    and isinstance(node.value, ast.Subscript)
                    and _self_attr(node.value.value) == attr):
                return True
        if isinstance(node, ast.Delete):
            for target in node.targets:
                if (isinstance(target, ast.Subscript)
                        and _self_attr(target.value) == attr):
                    return True
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "clear"
                    and _self_attr(node.func.value) == attr):
                return True
    return False


@rule("UNBOUNDED_QUEUE",
      "Server-module queue grows without a maxlen, bound check, or trim",
      family="concurrency",
      rationale="An ingest/backlog container with no visible bound turns "
                "overload into OOM: the process dies instead of shedding. "
                "Bound it (deque maxlen), check len() against a limit "
                "before growing (the admission idiom — see "
                "docs/overload.md), or trim after. Consumption alone is "
                "not a bound: a pump that drains slower than producers "
                "fill still grows forever.")
def unbounded_queue(ctx: ModuleContext) -> Iterator[Violation]:
    if not _queue_scope(ctx):
        return
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        unbounded: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = _self_attr(target)
                    if (attr is not None and _queueish(attr)
                            and _unbounded_container_init(node.value)):
                        unbounded.add(attr)
        if not unbounded:
            continue
        flagged: Set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GROWTH_METHODS):
                continue
            attr = _self_attr(node.func.value)
            if (attr is None or attr not in unbounded
                    or attr in flagged):
                continue
            if _bound_evidence(cls, attr):
                continue
            flagged.add(attr)
            yield ctx.violation(
                "UNBOUNDED_QUEUE", node,
                f"`self.{attr}` in `{cls.name}` grows via "
                f".{node.func.attr}() with no visible bound (no deque "
                f"maxlen, no len() limit check, no trim): overload must "
                f"hit admission control, not RAM")
