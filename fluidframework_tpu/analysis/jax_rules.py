"""JX* rules: JAX/TPU kernel hygiene inside jit-decorated functions.

Every rule here scopes itself to functions the engine indexed as jitted
(decorator or ``jax.jit(fn)`` call form), so host-side code never
trips them. They are heuristics over the AST — no dataflow — tuned to
stay quiet on the idioms this codebase deliberately uses (``is None``
staging guards, ``static_argnums`` flags, shape/dtype attribute reads).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set, Tuple

from .engine import ModuleContext, Violation, _dotted
from .registry import rule

DTYPE_NAME_RE = re.compile(
    r"^(u?int(8|16|32|64)|float(16|32|64)|bfloat16|bool_|complex(64|128))$")

_NUMPY_MODULES = ("jnp", "np", "numpy", "jax.numpy")


def _canonical_dtypes() -> Set[str]:
    try:
        from ..mergetree.constants import CANONICAL_DEVICE_DTYPES
        return set(CANONICAL_DEVICE_DTYPES)
    except ImportError:  # analyzer used standalone against another tree
        return {"int32", "bool_"}


def _within(ctx: ModuleContext, node: ast.AST, stop: ast.AST):
    """Ancestors of ``node`` up to and including ``stop``."""
    cur = ctx.parents.get(node)
    while cur is not None:
        yield cur
        if cur is stop:
            return
        cur = ctx.parents.get(cur)


def _is_static_read(ctx: ModuleContext, name: ast.Name,
                    test: ast.AST) -> bool:
    """True when this Name occurrence cannot force a concrete value out
    of a tracer: identity tests, isinstance/len(), or attribute reads
    (shape/ndim/dtype and namedtuple statics like ``.capacity``)."""
    for anc in _within(ctx, name, test):
        if isinstance(anc, ast.Attribute):
            return True
        if isinstance(anc, ast.Subscript) and anc.value is not name:
            continue
        if isinstance(anc, ast.Call):
            fn = _dotted(anc.func)
            if fn in ("isinstance", "len", "getattr", "hasattr", "type"):
                return True
        if isinstance(anc, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in anc.ops):
            return True
    if isinstance(ctx.parents.get(name), ast.Compare):
        comp = ctx.parents[name]
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in comp.ops):
            return True
    return False


def _hazard_names(ctx: ModuleContext, test: ast.AST,
                  traced: Set[str]) -> Set[str]:
    out: Set[str] = set()
    nodes = [test] + [n for n in ast.walk(test)]
    for node in nodes:
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id in traced
                and not _is_static_read(ctx, node, test)):
            out.add(node.id)
    return out


@rule("TRACED_BRANCH",
      "Python if/while branches on a traced value inside a jitted function",
      family="jax",
      rationale="A concrete branch on a tracer either raises at trace time "
                "or silently bakes one path into the compiled program; use "
                "jnp.where / lax.cond, or mark the argument static.")
def traced_branch(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        info = ctx.enclosing_jit(node)
        if info is None:
            continue
        names = _hazard_names(ctx, node.test, info.traced_params())
        if names:
            kind = "while" if isinstance(node, ast.While) else "if"
            yield ctx.violation(
                "TRACED_BRANCH", node,
                f"`{kind}` branches on traced argument(s) "
                f"{', '.join(sorted(names))} inside jitted "
                f"`{info.node.name}`; use jnp.where/lax.cond or add the "
                f"argument to static_argnums")


@rule("HOST_SYNC",
      "Host synchronization (.item()/.tolist()/bool()/int()/float() on a "
      "traced value) inside a jitted function",
      family="jax",
      rationale="Forcing a concrete Python value out of a tracer raises a "
                "ConcretizationTypeError at trace time — or, on the host "
                "staging path, blocks on a device round-trip per call.")
def host_sync(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        info = ctx.enclosing_jit(node)
        if info is None:
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("item", "tolist")
                and not node.args):
            yield ctx.violation(
                "HOST_SYNC", node,
                f"`.{node.func.attr}()` inside jitted "
                f"`{info.node.name}` forces a host sync")
            continue
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("bool", "int", "float")
                and len(node.args) == 1):
            names = _hazard_names(ctx, node.args[0], info.traced_params())
            if names:
                yield ctx.violation(
                    "HOST_SYNC", node,
                    f"`{node.func.id}()` concretizes traced argument(s) "
                    f"{', '.join(sorted(names))} inside jitted "
                    f"`{info.node.name}`")


def _jnp_calls(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = _dotted(sub.func)
            if fn.startswith(("jnp.", "jax.numpy.", "jax.lax.", "lax.")):
                yield sub


@rule("RETRACE_HAZARD",
      "jnp/lax calls inside a Python for/while loop in a jitted function",
      family="jax",
      rationale="A Python loop unrolls at trace time: program size (and "
                "compile time) scales with the trip count, and a "
                "data-dependent count retraces per shape. Use lax.scan/"
                "fori_loop, or suppress when the unroll is deliberately "
                "bounded (e.g. the per-bucket serve_window unroll).")
def retrace_hazard(ctx: ModuleContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        info = ctx.enclosing_jit(node)
        if info is None:
            continue
        calls = list(_jnp_calls(node))
        if calls:
            yield ctx.violation(
                "RETRACE_HAZARD", node,
                f"Python loop inside jitted `{info.node.name}` unrolls "
                f"{len(calls)} jnp/lax call(s) at trace time; prefer "
                f"lax.scan/fori_loop")


def _module_mutable_globals(ctx: ModuleContext) -> Set[str]:
    out: Set[str] = set()
    for stmt in ctx.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call) and _dotted(value.func) in (
                "list", "dict", "set", "collections.defaultdict",
                "defaultdict", "collections.deque", "deque", "bytearray"):
            mutable = True
        if mutable:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


@rule("MUTABLE_CAPTURE",
      "Jitted function reads a module-level mutable (list/dict/set) global",
      family="jax",
      rationale="jit captures closed-over values at trace time; later "
                "mutations are invisible to the compiled program (or force "
                "a retrace via a changed hash). Pass the data as an "
                "argument or freeze it into a tuple/constant.")
def mutable_capture(ctx: ModuleContext) -> Iterator[Violation]:
    mutables = _module_mutable_globals(ctx)
    if not mutables:
        return
    for fn, info in ctx.jit_functions.items():
        local: Set[str] = {a.arg for a in fn.args.args}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                local.add(sub.id)
        seen: Set[str] = set()
        for sub in ast.walk(fn):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in mutables and sub.id not in local
                    and sub.id not in seen):
                seen.add(sub.id)
                yield ctx.violation(
                    "MUTABLE_CAPTURE", sub,
                    f"jitted `{fn.name}` reads module-level mutable "
                    f"global `{sub.id}`; trace-time capture freezes it")


@rule("DTYPE_DRIFT",
      "Device dtype literal outside the canonical set from "
      "mergetree/constants.py",
      family="jax",
      rationale="The device schema is int32 columns + bool_ masks "
                "(CANONICAL_DEVICE_DTYPES); a stray int64/float literal "
                "silently doubles a column's bytes or forces an x64 "
                "fallback. Deliberate narrow packing (e.g. the int16 wire "
                "result) carries an inline suppression.")
def dtype_drift(ctx: ModuleContext) -> Iterator[Violation]:
    canonical = _canonical_dtypes()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Attribute):
            continue
        if not DTYPE_NAME_RE.match(node.attr):
            continue
        if _dotted(node.value) not in _NUMPY_MODULES:
            continue
        if node.attr in canonical:
            continue
        info = ctx.enclosing_jit(node)
        if info is None:
            continue
        yield ctx.violation(
            "DTYPE_DRIFT", node,
            f"dtype `{_dotted(node.value)}.{node.attr}` in jitted "
            f"`{info.node.name}` drifts from the canonical device dtypes "
            f"({', '.join(sorted(canonical))})")


# Host-callback hazards inside scan/while_loop bodies: the fused burst
# program (serve_step.serve_burst) runs pack→apply→extract for K windows
# inside ONE lax.scan precisely to remove per-window host round-trips —
# an io_callback/debug.callback re-entering the host per scan step (or a
# block_until_ready forcing a device sync at trace/staging time) would
# silently reintroduce the serialized RPC the fusion exists to delete,
# K times per burst. The R10 megakernel raises the stakes: a
# pl.pallas_call kernel body IS the persistent device program — a host
# callback there cannot lower at all on TPU (the fallback would eat the
# whole kernel, silently), and a sync at trace time stalls the one-in-
# flight megakernel ring.
_SCAN_DRIVER_BODY_ARGS = {
    "scan": (0,),          # lax.scan(body, init, xs)
    "while_loop": (0, 1),  # lax.while_loop(cond_fun, body_fun, init)
    "fori_loop": (2,),     # lax.fori_loop(lo, hi, body_fun, init)
    "pallas_call": (0,),   # pl.pallas_call(kernel, out_shape=..., ...)
}

# pallas_call is not a lax symbol; it arrives as pl.pallas_call /
# pallas.pallas_call / fully qualified. Bare "scan" is too generic to
# match unqualified; "pallas_call" is not.
_PALLAS_HEADS = ("pl", "pallas", "jax.experimental.pallas", "")

_HOST_CALLBACK_NAMES = {
    "io_callback", "jax.experimental.io_callback",
    "debug.callback", "jax.debug.callback",
    "host_callback.call", "jax.experimental.host_callback.call",
    "hcb.call", "pure_callback", "jax.pure_callback",
}

# Same operational scope as SPAN_LEAK: the op pipeline's device code.
_SCAN_SCOPE_PREFIXES = (
    "fluidframework_tpu/mergetree", "fluidframework_tpu/server",
    "<memory>")


def _scan_scope(ctx: ModuleContext) -> bool:
    path = ctx.path.replace("\\", "/")
    return any(path.startswith(p) or f"/{p}" in path
               for p in _SCAN_SCOPE_PREFIXES)


def _scan_driver(call: ast.Call) -> Optional[Tuple[str, tuple]]:
    """(driver name, body-arg positions) when `call` is a lax.scan /
    while_loop / fori_loop invocation (plain or jax.lax-qualified)."""
    fn = _dotted(call.func)
    if not fn:
        return None
    head, _, tail = fn.rpartition(".")
    if tail == "pallas_call" and head in _PALLAS_HEADS:
        return tail, _SCAN_DRIVER_BODY_ARGS[tail]
    if tail in _SCAN_DRIVER_BODY_ARGS and head in ("lax", "jax.lax", ""):
        # Bare names ("scan") only count when qualified — too generic
        # otherwise.
        if head or tail in ("while_loop", "fori_loop"):
            return tail, _SCAN_DRIVER_BODY_ARGS[tail]
    return None


def _body_functions(ctx: ModuleContext, call: ast.Call,
                    positions: tuple):
    """Resolve a scan driver call's body argument(s) to AST function
    nodes: inline lambdas directly, Name references to module-level (or
    nested) defs by name, functools.partial(f, ...) through its first
    argument. Unresolvable bodies (imports, attributes) are skipped —
    the rule is single-module by design, like every fluidlint check."""
    by_name: dict = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)
    exprs = []
    for pos in positions:
        if pos < len(call.args):
            exprs.append(call.args[pos])
    for kw in call.keywords:
        if kw.arg in ("f", "body_fun", "cond_fun", "kernel") \
                and kw.value not in exprs:
            exprs.append(kw.value)
    for expr in exprs:
        if isinstance(expr, ast.Call) and \
                _dotted(expr.func) in ("functools.partial", "partial") \
                and expr.args:
            expr = expr.args[0]
        if isinstance(expr, ast.Lambda):
            yield "<lambda>", expr
        elif isinstance(expr, ast.Name):
            for fn in by_name.get(expr.id, []):
                yield expr.id, fn


def _host_callback_hazards(body: ast.AST):
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        if fn in _HOST_CALLBACK_NAMES or \
                fn.rpartition(".")[2] == "io_callback":
            yield node, fn
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "block_until_ready"
              and not node.args):
            yield node, ".block_until_ready()"


@rule("SCAN_HOST_CALLBACK",
      "Host callback / device sync inside a scanned or pallas kernel body",
      family="jax",
      rationale="A scanned body re-entering the host (io_callback, "
                "debug.callback, pure_callback) or forcing a sync "
                "(.block_until_ready()) serializes every scan step on a "
                "host round-trip — exactly the per-window RPC the fused "
                "serving burst exists to remove. Inside a pl.pallas_call "
                "kernel the same constructs cannot lower at all: the "
                "megakernel would silently fall back to the scan path "
                "every ring. Move the host work to the carry/ys "
                "boundary, or keep the value device-side.")
def scan_host_callback(ctx: ModuleContext) -> Iterator[Violation]:
    if not _scan_scope(ctx):
        return
    seen: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        driver = _scan_driver(node)
        if driver is None:
            continue
        name, positions = driver
        for body_name, body in _body_functions(ctx, node, positions):
            for hazard, what in _host_callback_hazards(body):
                key = id(hazard)
                if key in seen:
                    continue
                seen.add(key)
                if name == "pallas_call":
                    yield ctx.violation(
                        "SCAN_HOST_CALLBACK", hazard,
                        f"`{what}` inside `{body_name}`, a "
                        f"`pl.pallas_call` kernel body: the kernel is a "
                        f"persistent device program — host re-entry "
                        f"cannot lower, and a sync stalls the "
                        f"megakernel ring")
                else:
                    yield ctx.violation(
                        "SCAN_HOST_CALLBACK", hazard,
                        f"`{what}` inside `{body_name}`, the body of a "
                        f"`lax.{name}`: every step pays a host "
                        f"round-trip, serializing the scanned program")


# serve/window joined step/apply when serve_window gained lane-state
# donation (donate_argnums=(0, 2, 4)): the serving window threads the
# ticket state plus every merge/LWW lane plane per flush, so a dropped
# donation there doubles peak HBM on the hottest path in the system.
# Word-ish anchoring so names that merely CONTAIN a keyword (observe,
# reserved, stepper-adjacent helpers like `misapply`) don't fire.
_STEP_NAME_RE = re.compile(r"(^|_)(step|apply|serve|window)",
                           re.IGNORECASE)


def _threads_state(fn: ast.FunctionDef) -> bool:
    if not fn.args.args:
        return False
    first = fn.args.args[0].arg
    return first == "state" or first.endswith("state")


@rule("MISSING_DONATE",
      "State-threading step/apply function jitted without donate_argnums",
      family="jax",
      rationale="A step function that returns the next state without "
                "donating the previous one doubles peak device memory for "
                "every column it threads. Non-donating variants kept for "
                "retry paths carry an inline suppression explaining why.")
def missing_donate(ctx: ModuleContext) -> Iterator[Violation]:
    for fn, info in ctx.jit_functions.items():
        if info.donate_argnums or info.donate_argnames:
            continue
        if not (_STEP_NAME_RE.search(fn.name) and _threads_state(fn)):
            continue
        yield ctx.violation(
            "MISSING_DONATE", fn,
            f"jitted `{fn.name}` threads `{fn.args.args[0].arg}` but "
            f"declares no donate_argnums; the previous state stays live "
            f"across the step")
    # Call-form jit over a function we could NOT resolve in this module
    # (e.g. jax.jit(full_step) over an import): flag by name pattern.
    resolved = {info.node.name for info in ctx.jit_functions.values()}
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _dotted(node.func)
                in ("jax.jit", "jit") and node.args):
            continue
        target = node.args[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id in resolved:
            continue
        if any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in node.keywords):
            continue
        if _STEP_NAME_RE.search(target.id):
            yield ctx.violation(
                "MISSING_DONATE", node,
                f"`jax.jit({target.id})` without donate_argnums; if "
                f"`{target.id}` threads state, the previous buffers stay "
                f"live across every step")


# PAGE_ID_DTYPE moved to lifecycle_rules.py in v2: the regex that only
# saw page-NAMED assignments became a dtype lattice propagated through
# astype/asarray/arithmetic by the dataflow pass (analysis/dataflow.py),
# so drift through intermediate bindings is caught too. Rule id,
# scope, and message shape are unchanged.
