"""Baseline: accepted pre-existing violations, committed next to the rules.

Each entry pins one violation by fingerprint (rule + path + enclosing
symbol + normalized source line — line *numbers* deliberately excluded
so unrelated edits above a finding don't invalidate it) together with a
human-readable reason. The CLI fails on any violation not in the
baseline; ``--write-baseline`` regenerates the file from the current
findings, preserving reasons for fingerprints that survive.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List

from .engine import Violation

DEFAULT_BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"


class Baseline:
    def __init__(self, entries: Iterable[dict] = ()):
        self.entries: List[dict] = list(entries)
        self._by_fp: Dict[str, dict] = {e["fingerprint"]: e
                                        for e in self.entries}

    # -- io ----------------------------------------------------------------
    @classmethod
    def load(cls, path: Path = DEFAULT_BASELINE_PATH) -> "Baseline":
        if not Path(path).exists():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(data.get("entries", []))

    def save(self, path: Path = DEFAULT_BASELINE_PATH) -> None:
        payload = {
            "version": 1,
            "comment": "fluidlint accepted violations; regenerate with "
                       "python -m fluidframework_tpu.analysis "
                       "--write-baseline, then fill in reasons.",
            "entries": sorted(self.entries,
                              key=lambda e: (e["path"], e["rule"],
                                             e["fingerprint"])),
        }
        Path(path).write_text(json.dumps(payload, indent=2) + "\n")

    # -- queries -----------------------------------------------------------
    def contains(self, violation: Violation) -> bool:
        return violation.fingerprint in self._by_fp

    def reason(self, violation: Violation) -> str:
        entry = self._by_fp.get(violation.fingerprint)
        return entry.get("reason", "") if entry else ""

    def __len__(self) -> int:
        return len(self.entries)

    # -- construction ------------------------------------------------------
    def updated_with(self, violations: Iterable[Violation]) -> "Baseline":
        """New baseline covering exactly ``violations``; reasons carry
        over for fingerprints already accepted."""
        entries = []
        for v in violations:
            prior = self._by_fp.get(v.fingerprint, {})
            entries.append({
                "rule": v.rule_id,
                "path": v.path,
                "symbol": v.symbol,
                "line": v.line,  # informational; matching uses fingerprint
                "text": v.line_text,
                "fingerprint": v.fingerprint,
                "reason": prior.get("reason", "TODO: justify or fix"),
            })
        return Baseline(entries)
