"""DataStoreRuntime: per-data-store channel registry + op routing.

Capability parity with reference packages/runtime/datastore/src/
dataStoreRuntime.ts:89 (createChannel :340, bindChannel :375, process :472,
submitMessage :698): owns the DDS channels of one data store, routes channel
ops by address, aggregates summaries, and fans reconnect resubmission out to
channels.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, TYPE_CHECKING

from ..core.events import TypedEventEmitter
from ..dds.shared_object import SharedObject
from ..protocol.summary import SummaryTree

if TYPE_CHECKING:
    from .container_runtime import ContainerRuntime


class ChannelRegistry:
    """IChannelFactory registry (reference datastore-definitions channel.ts:134):
    maps DDS type names to constructors."""

    def __init__(self):
        self._factories: Dict[str, Any] = {}

    def register(self, cls) -> None:
        self._factories[cls.TYPE] = cls

    def create(self, type_name: str, object_id: str) -> SharedObject:
        if type_name not in self._factories:
            raise KeyError(f"no channel factory for {type_name!r}")
        return self._factories[type_name](object_id)

    def types(self) -> List[str]:
        return list(self._factories)


def default_registry() -> ChannelRegistry:
    from ..dds.map import SharedMap
    from ..dds.sequence import (SharedString, SharedSegmentSequence,
                                SharedNumberSequence, SharedObjectSequence)
    from ..dds.counter import SharedCounter
    from ..dds.cell import SharedCell
    from ..dds.directory import SharedDirectory
    from ..dds.register_collection import ConsensusRegisterCollection
    from ..dds.ordered_collection import ConsensusQueue
    from ..dds.matrix import SharedMatrix
    from ..dds.ink import Ink
    from ..dds.summary_block import SharedSummaryBlock
    from ..dds.sparse_matrix import SparseMatrix
    reg = ChannelRegistry()
    for cls in (SharedMap, SharedString, SharedSegmentSequence, SharedCounter,
                SharedCell, SharedDirectory, ConsensusRegisterCollection,
                ConsensusQueue, SharedMatrix, Ink, SharedSummaryBlock,
                SparseMatrix, SharedNumberSequence, SharedObjectSequence):
        reg.register(cls)
    return reg


class DataStoreRuntime(TypedEventEmitter):
    def __init__(self, store_id: str, container: "ContainerRuntime",
                 registry: Optional[ChannelRegistry] = None):
        super().__init__()
        self.id = store_id
        self.container = container
        self.registry = registry or default_registry()
        self.channels: Dict[str, SharedObject] = {}
        # Channels created while live whose attach op is unacked; maps id ->
        # the attach summary captured AT CREATION (resubmits reuse it — a
        # re-captured summary would double-count data ops that are also
        # resubmitted as pendings). Reference: LocalChannelContext attach.
        self._pending_attach: Dict[str, dict] = {}

    @property
    def client_ordinal(self) -> int:
        return self.container.client_ordinal

    @property
    def attached(self) -> bool:
        return self.container.attached

    @property
    def audience(self):
        """The container's connected-client roster (reference
        IFluidDataStoreRuntime.getAudience()); None when unattached."""
        return self.container.audience

    # -- channels ----------------------------------------------------------
    def create_channel(self, object_id: str, type_name: str) -> SharedObject:
        channel = self.registry.create(type_name, object_id)
        channel.bind_to_runtime(self)
        if self.attached:
            channel.connect()
            # Live creation: ship an attach op so remote replicas build a
            # remote channel context (reference dataStoreRuntime.ts:340
            # createChannel -> bindChannel attach path).
            from ..protocol.summary import summary_tree_to_dict
            attach = {"id": object_id, "type": type_name,
                      "summary": summary_tree_to_dict(channel.summarize())}
            self._pending_attach[object_id] = attach
            self.container.submit_datastore_op(self.id, {"attach": attach})
        return channel

    def bind_channel(self, channel: SharedObject) -> None:
        if channel.id in self.channels:
            raise ValueError(f"duplicate channel id {channel.id!r}")
        self.channels[channel.id] = channel
        channel.runtime = self

    def get_channel(self, object_id: str) -> SharedObject:
        return self.channels[object_id]

    # -- op plumbing -------------------------------------------------------
    def submit_channel_op(self, channel_id: str, contents: Any) -> None:
        self.container.submit_datastore_op(
            self.id, {"address": channel_id, "contents": contents})

    # -- signals (reference dataStoreRuntime submitSignal/processSignal) ---
    def submit_signal(self, signal_type: str, content: Any) -> None:
        """Broadcast a transient signal scoped to this datastore; peers
        receive it as a ("signal", type, content, local, client_id) event
        on their DataStoreRuntime instance."""
        self.container.submit_signal(signal_type, content, address=self.id)

    def process_signal(self, envelope: dict, local: bool,
                       client_id) -> None:
        self.emit("signal", envelope.get("type"), envelope.get("content"),
                  local, client_id)

    def process(self, envelope: dict, local: bool, seq: int, ref_seq: int,
                client_ordinal: int, min_seq: int) -> None:
        if "attach" in envelope:
            self._process_attach(envelope["attach"], local)
            return
        channel = self.channels[envelope["address"]]
        channel.process(envelope["contents"], local, seq, ref_seq,
                        client_ordinal, min_seq)

    def _process_attach(self, info: dict, local: bool) -> None:
        """Build a remote channel context from a live attach op (reference
        remoteChannelContext.ts:34). Duplicate ids (concurrent same-id
        creation) keep the first; later data ops still converge because both
        replicas apply the same sequenced stream."""
        if local:
            self._pending_attach.pop(info["id"], None)
            return
        if info["id"] in self.channels:
            return
        from ..protocol.summary import summary_tree_from_dict
        channel = self.registry.create(info["type"], info["id"])
        channel.runtime = self
        self.channels[info["id"]] = channel
        channel.load_core(summary_tree_from_dict(info["summary"]))
        adopt = getattr(channel, "adopt_client_ordinal", None)
        if adopt:
            adopt(self.client_ordinal)
        channel.connect()

    def resubmit_pending(self) -> List[dict]:
        # Unacked attach ops go first: the channels' data ops land on
        # replicas that must already have the channel.
        ops: List[dict] = [{"attach": attach}
                           for attach in self._pending_attach.values()]
        for channel_id, channel in self.channels.items():
            for contents in channel.resubmit_pending():
                ops.append({"address": channel_id, "contents": contents})
        return ops

    # -- attach / summary --------------------------------------------------
    def connect(self) -> None:
        for channel in self.channels.values():
            channel.connect()

    def summarize(self, incremental: bool = False,
                  acked_epochs: Optional[Dict[str, int]] = None
                  ) -> SummaryTree:
        """incremental=True: channels unchanged since the last ACKED summary
        serialize as a handle to the previous summary's same-position
        subtree (reference trackState/SummaryTracker; the storage layer
        resolves handles against the parent commit)."""
        from ..protocol.summary import SummaryHandle
        acked_epochs = acked_epochs or {}
        tree = SummaryTree()
        channels = tree.add_tree(".channels")
        for channel_id, channel in sorted(self.channels.items()):
            key = f"{self.id}/{channel_id}"
            if incremental and acked_epochs.get(key) == channel.change_epoch:
                channels.entries[channel_id] = SummaryHandle("/")
            else:
                channels.entries[channel_id] = channel.summarize()
        return tree

    def channel_epochs(self) -> Dict[str, int]:
        return {f"{self.id}/{cid}": ch.change_epoch
                for cid, ch in self.channels.items()}

    def load(self, tree: SummaryTree) -> None:
        import json
        channels = tree.entries[".channels"]
        for channel_id, sub in channels.entries.items():
            attrs = json.loads(sub.entries[".attributes"].content)
            channel = self.registry.create(attrs["type"], channel_id)
            channel.runtime = self
            self.channels[channel_id] = channel
            channel.load_core(sub)
            if self.attached:
                channel.connect()

    def get_gc_data(self) -> Dict[str, List[str]]:
        return {f"/{self.id}/{cid}": ch.get_gc_data()
                for cid, ch in self.channels.items()}
