"""PendingStateManager: tracks in-flight local ops for ack and reconnect.

Capability parity with reference packages/runtime/container-runtime/src/
pendingStateManager.ts:56 — every submitted op is recorded; sequenced own
ops must ack in submission order (a mismatch is data corruption); on
reconnect the recorded ops are discarded and channels regenerate their
pending work (merge-tree rewrites positions, map re-emits sets).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional


class DataCorruptionError(Exception):
    """Ack arrived out of order vs submission (reference DataCorruptionError)."""


@dataclass
class PendingOp:
    client_sequence_number: int
    contents: Any


class PendingStateManager:
    def __init__(self):
        self._pending: List[PendingOp] = []
        # Ops still in flight under previous connections' client ids. An op
        # submitted just before a disconnect may still get sequenced under
        # the OLD id; recognizing it here acks it instead of double-applying
        # it (once as "remote", once via reconnect resubmission).
        self._prior: Dict[str, List[PendingOp]] = {}

    @property
    def count(self) -> int:
        return len(self._pending) + sum(len(v) for v in self._prior.values())

    def has_prior(self, client_id) -> bool:
        """True when ops of OURS may still arrive under this (previous
        connection's) client id — such messages need try_prior_ack
        pairing, so they must never ride a remote bulk run."""
        return client_id in self._prior

    def on_submit(self, client_sequence_number: int, contents: Any) -> None:
        self._pending.append(PendingOp(client_sequence_number, contents))

    def on_local_ack(self, client_sequence_number: int) -> PendingOp:
        if not self._pending:
            raise DataCorruptionError(
                f"ack for csn {client_sequence_number} with nothing pending")
        head = self._pending.pop(0)
        if head.client_sequence_number != client_sequence_number:
            raise DataCorruptionError(
                f"out-of-order ack: expected csn "
                f"{head.client_sequence_number}, got {client_sequence_number}")
        return head

    def on_connection_change(self, old_client_id: Optional[str]) -> None:
        """Archive in-flight ops under the id they were submitted with; they
        either arrive sequenced under that id (try_prior_ack) or get
        regenerated at the next connect (drain)."""
        if old_client_id is not None and self._pending:
            self._prior.setdefault(old_client_id, []).extend(self._pending)
            self._pending = []

    def try_prior_ack(self, client_id: str, client_sequence_number: int
                      ) -> Optional[PendingOp]:
        """If (client_id, csn) is the head of a previous connection's
        in-flight queue, this sequenced message is one of OURS: pop it so
        reconnect does not resubmit it, and ack it as local."""
        queue = self._prior.get(client_id)
        if queue and queue[0].client_sequence_number == client_sequence_number:
            op = queue.pop(0)
            if not queue:
                del self._prior[client_id]
            return op
        return None

    def drain(self) -> List[PendingOp]:
        """Take all in-flight ops (reconnect: they are re-generated, not
        replayed verbatim)."""
        out = self._pending
        for queue in self._prior.values():
            out.extend(queue)
        self._pending, self._prior = [], {}
        return out
