"""PendingStateManager: tracks in-flight local ops for ack and reconnect.

Capability parity with reference packages/runtime/container-runtime/src/
pendingStateManager.ts:56 — every submitted op is recorded; sequenced own
ops must ack in submission order (a mismatch is data corruption); on
reconnect the recorded ops are discarded and channels regenerate their
pending work (merge-tree rewrites positions, map re-emits sets).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List


class DataCorruptionError(Exception):
    """Ack arrived out of order vs submission (reference DataCorruptionError)."""


@dataclass
class PendingOp:
    client_sequence_number: int
    contents: Any


class PendingStateManager:
    def __init__(self):
        self._pending: List[PendingOp] = []

    @property
    def count(self) -> int:
        return len(self._pending)

    def on_submit(self, client_sequence_number: int, contents: Any) -> None:
        self._pending.append(PendingOp(client_sequence_number, contents))

    def on_local_ack(self, client_sequence_number: int) -> PendingOp:
        if not self._pending:
            raise DataCorruptionError(
                f"ack for csn {client_sequence_number} with nothing pending")
        head = self._pending.pop(0)
        if head.client_sequence_number != client_sequence_number:
            raise DataCorruptionError(
                f"out-of-order ack: expected csn "
                f"{head.client_sequence_number}, got {client_sequence_number}")
        return head

    def drain(self) -> List[PendingOp]:
        """Take all in-flight ops (reconnect: they are re-generated, not
        replayed verbatim)."""
        out, self._pending = self._pending, []
        return out
