"""Summarization subsystem: election, heuristics, ack tracking, GC.

Capability parity with reference packages/runtime/container-runtime/src/
{summaryManager.ts, summarizer.ts:153-280, summaryCollection.ts:197} and
packages/runtime/garbage-collector/src/garbageCollector.ts:

- SummaryManager: every client watches the quorum; the OLDEST interactive
  client (minimum join sequence number) is the electee and runs the
  summarizer (summaryManager.ts:50-61). Here the summarizer runs in-process
  on the elected container rather than spawning a hidden "/_summarizer"
  client — one client fewer in the quorum, same election semantics.
- RunningSummarizer + SummarizerHeuristics: summarize after maxOps ops,
  after idleTime with no ops, or after maxTime since the last acked
  summary, with nack retries (summarizer.ts:153-280).
- SummaryCollection: watches summarize/summaryAck/summaryNack in the op
  stream; exposes the latest acked summary and waiters
  (summaryCollection.ts:197,244).
- run_garbage_collection: mark pass over the handle-reference graph built
  from each node's getGCData (garbageCollector.ts; sharedObject.ts:244).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set


# ---------------------------------------------------------------------------
# Garbage collection
# ---------------------------------------------------------------------------

@dataclass
class GCResult:
    referenced: List[str]
    unreferenced: List[str]


def run_garbage_collection(nodes: Dict[str, List[str]],
                           roots: List[str]) -> GCResult:
    """Mark reachable nodes from `roots` over the outbound-route graph.

    `nodes` maps node id (e.g. "/store/channel") -> outbound routes it
    references. Routes may point at nodes or at their prefixes ("/store"
    references every "/store/..." node implicitly, matching the reference's
    route-to-node normalization)."""
    ids = sorted(nodes)
    visited: Set[str] = set()
    stack = [r for r in roots]
    while stack:
        route = stack.pop()
        targets = [n for n in ids
                   if n == route or n.startswith(route.rstrip("/") + "/")]
        for node_id in targets:
            if node_id in visited:
                continue
            visited.add(node_id)
            stack.extend(nodes[node_id])
    return GCResult(
        referenced=[n for n in ids if n in visited],
        unreferenced=[n for n in ids if n not in visited])


# ---------------------------------------------------------------------------
# Summary ack tracking
# ---------------------------------------------------------------------------

class SummaryCollection:
    """Feed every sequenced message via process(); tracks proposals and the
    latest acked summary (summaryCollection.ts)."""

    def __init__(self):
        self.last_ack: Optional[dict] = None  # {handle, summarySequenceNumber}
        self.pending: Dict[int, dict] = {}    # summarySeq -> summarize info
        self._waiters: List[Callable[[bool, dict], None]] = []

    def process(self, message) -> None:
        from ..protocol.messages import MessageType
        mtype = message.type
        if mtype == MessageType.SUMMARIZE:
            contents = message.contents or {}
            self.pending[message.sequence_number] = {
                "clientId": message.client_id,
                "handle": contents.get("handle"),
            }
        elif mtype in (MessageType.SUMMARY_ACK, MessageType.SUMMARY_NACK):
            contents = message.contents or {}
            proposal = contents.get("summaryProposal", {})
            summary_seq = proposal.get("summarySequenceNumber")
            info = self.pending.pop(summary_seq, {})
            ack = mtype == MessageType.SUMMARY_ACK
            if ack:
                self.last_ack = {
                    "handle": contents.get("handle", info.get("handle")),
                    "summarySequenceNumber": summary_seq,
                }
            waiters, self._waiters = self._waiters, []
            for fn in waiters:
                fn(ack, contents)

    def wait_summary_ack(self, fn: Callable[[bool, dict], None]) -> None:
        self._waiters.append(fn)


# ---------------------------------------------------------------------------
# Heuristics + running summarizer
# ---------------------------------------------------------------------------

@dataclass
class SummaryConfig:
    """Reference defaults: idle 5 s, max 120 s, 1000-op threshold."""

    idle_time: float = 5.0
    max_time: float = 120.0
    max_ops: int = 1000
    min_ops: int = 1
    max_attempts: int = 3


class RunningSummarizer:
    """Drives Container.summarize from op-stream heuristics. Feed ops with
    on_op(); advance wall-clock triggers with tick() (the host pump calls it;
    tests inject a fake clock)."""

    def __init__(self, container, config: Optional[SummaryConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.container = container
        self.config = config or SummaryConfig()
        self.clock = clock
        self.ops_since_ack = 0
        self.last_op_time = clock()
        self.last_summary_time = clock()
        self.summarizing = False
        self.attempts = 0
        self.stopped = False
        self.summaries_run = 0

    # -- inputs ------------------------------------------------------------
    def on_op(self, message) -> None:
        from ..protocol.messages import MessageType
        if self.stopped or message.type != MessageType.OPERATION:
            return
        self.ops_since_ack += 1
        self.last_op_time = self.clock()
        if self.ops_since_ack >= self.config.max_ops:
            self._try_summarize("maxOps")

    def tick(self) -> None:
        """Time-based triggers (idle / maxTime)."""
        if self.stopped or self.summarizing:
            return
        if self.ops_since_ack < self.config.min_ops:
            return
        now = self.clock()
        if now - self.last_op_time >= self.config.idle_time:
            self._try_summarize("idle")
        elif now - self.last_summary_time >= self.config.max_time:
            self._try_summarize("maxTime")

    def stop(self) -> None:
        self.stopped = True

    # -- internals ---------------------------------------------------------
    def _try_summarize(self, reason: str) -> None:
        if self.summarizing or self.stopped:
            return
        self.summarizing = True
        self.attempts += 1

        def on_result(handle, ack, contents):
            self.summarizing = False
            if ack:
                self.ops_since_ack = 0
                self.attempts = 0
                self.last_summary_time = self.clock()
                self.summaries_run += 1
            elif self.attempts < self.config.max_attempts:
                self._try_summarize(f"{reason}-retry")
            else:
                self.attempts = 0  # give up this round; heuristics re-arm

        self.container.summarize(on_result)


# ---------------------------------------------------------------------------
# Election
# ---------------------------------------------------------------------------

class SummaryManager:
    """Summarizer election (summaryManager.ts): the interactive client with
    the lowest join sequence number is the electee; each client runs one of
    these and starts/stops its own RunningSummarizer as election flips."""

    def __init__(self, container, config: Optional[SummaryConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.container = container
        self.config = config or SummaryConfig()
        self.clock = clock
        self.running: Optional[RunningSummarizer] = None
        container.on("op", self._on_op)
        container.on("connected", self._refresh)
        container.on("disconnected", self._refresh)

    # -- election ----------------------------------------------------------
    def electee(self) -> Optional[str]:
        members = self.container.protocol.quorum.members
        candidates = [
            (client.sequence_number, client_id)
            for client_id, client in members.items()
            if _interactive(client.details)]
        return min(candidates)[1] if candidates else None

    @property
    def elected_self(self) -> bool:
        cid = self.container.delta_manager.client_id
        return cid is not None and self.electee() == cid

    # -- wiring ------------------------------------------------------------
    def _refresh(self, *_args) -> None:
        should_run = self.container.connected and self.elected_self
        if should_run and self.running is None:
            self.running = RunningSummarizer(self.container, self.config,
                                             self.clock)
        elif not should_run and self.running is not None:
            self.running.stop()
            self.running = None

    def _on_op(self, message, *_args) -> None:
        self._refresh()
        if self.running is not None:
            self.running.on_op(message)

    def tick(self) -> None:
        if self.running is not None:
            self.running.tick()


def _interactive(details: Any) -> bool:
    if isinstance(details, dict):
        caps = details.get("capabilities") or details.get("details", {})
        if isinstance(caps, dict) and "interactive" in caps:
            return bool(caps["interactive"])
        if "interactive" in details:
            return bool(details["interactive"])
    return True
