"""BlobManager: attachment blobs uploaded outside the op stream.

Capability parity with reference container-runtime/src/blobManager.ts:42 —
binary payloads too large/opaque for ops are stored content-addressed and
referenced from DDS values by handle path ("/_blobs/<sha>"); they persist
through the summary tree and participate in GC via those handle routes.
"""

from __future__ import annotations

import base64
from typing import Dict, List

from ..dds.shared_object import FluidHandle
from ..protocol.summary import SummaryTree, blob_sha

BLOBS_PATH = "_blobs"


class BlobManager:
    def __init__(self):
        self._blobs: Dict[str, bytes] = {}

    def create_blob(self, content: bytes) -> FluidHandle:
        if isinstance(content, str):
            content = content.encode()
        sha = blob_sha(content)
        self._blobs[sha] = content
        return FluidHandle(f"/{BLOBS_PATH}/{sha}", content)

    def get_blob(self, sha: str) -> bytes:
        return self._blobs[sha]

    def __len__(self) -> int:
        return len(self._blobs)

    def node_ids(self) -> List[str]:
        return [f"/{BLOBS_PATH}/{sha}" for sha in self._blobs]

    def summarize(self) -> SummaryTree:
        tree = SummaryTree()
        for sha, content in sorted(self._blobs.items()):
            # base64 keeps the summary tree JSON-safe for any byte payload.
            tree.add_blob(sha, base64.b64encode(content).decode())
        return tree

    def load(self, tree: SummaryTree) -> None:
        for sha, blob in tree.entries.items():
            self._blobs[sha] = base64.b64decode(blob.content)
