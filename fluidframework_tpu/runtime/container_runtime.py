"""ContainerRuntime: the per-container op router + lifecycle.

Capability parity with reference packages/runtime/container-runtime/src/
containerRuntime.ts:440 (process :1002, submit :1506, reSubmit :1627,
createSummary :1317) — with the reference's two-level routing
(ContainerRuntime -> FluidDataStoreContext -> FluidDataStoreRuntime)
collapsed to one explicit level (SURVEY.md §7.4: one level of routing is
enough in a new design).

Responsibilities here: datastore registry + envelope routing, op batching,
pending-state tracking with in-order ack enforcement, client-ordinal
interning from quorum join order, reconnect resubmission, summary tree
assembly, and GC data collection.

The runtime talks *down* to a delta submission function (driver/sequencer)
and receives *up* sequenced messages via process(); the loader Container
owns the protocol handler and connection state.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..core.events import TypedEventEmitter
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..protocol.summary import SummaryTree
from .blob_manager import BlobManager
from .datastore_runtime import ChannelRegistry, DataStoreRuntime
from .pending_state import PendingStateManager
from .summarizer import GCResult, run_garbage_collection


class ContainerRuntime(TypedEventEmitter):
    # Ops whose serialized contents exceed this split into CHUNKED_OP
    # messages (reference containerRuntime.ts:1444 submitChunkedMessage /
    # :1557 processRemoteChunkedMessage; IContainerRuntimeOptions
    # maxOpSizeInBytes). Overridable via options={"maxOpSize": n}.
    DEFAULT_MAX_OP_SIZE = 768 * 1024

    def __init__(self, submit_fn: Optional[Callable[[str, Any], int]] = None,
                 registry: Optional[ChannelRegistry] = None,
                 options: Optional[Dict[str, Any]] = None):
        super().__init__()
        self._submit_fn = submit_fn  # (type, contents) -> client_seq_number
        self._submit_signal_fn: Optional[Callable[[Any], None]] = None
        # Batch submission (DeltaManager.submit_batch): one boxcar for a
        # whole order_sequentially batch => contiguous sequencing. None
        # under mock runtimes -> falls back to per-op sends.
        self._submit_batch_fn: Optional[Callable] = None
        # Connected-client roster, set by the owning Container (reference
        # IFluidDataStoreRuntime.getAudience()); None under mock runtimes.
        self.audience = None
        # Signals flow on any live delta connection — including read-only
        # containers, which never go op-connected (no join op) but still
        # broadcast presence (reference: readers submit signals).
        self.signals_live = False
        # Read-only containers REJECT local mutations outright: an
        # optimistic local edit that can never submit would pend forever
        # and shadow all future remote updates on this replica.
        self.read_only = False
        self.registry = registry
        self.options = dict(options or {})
        self.max_op_size = int(self.options.get(
            "maxOpSize", self.DEFAULT_MAX_OP_SIZE))
        # Partial chunked-op reassembly per sending client id
        # (reference chunkMap, containerRuntime.ts:1557).
        self._chunk_buffers: Dict[str, List[str]] = {}
        # Datastores created while live whose attach op is unacked.
        self._pending_store_attach: Dict[str, dict] = {}
        # Incremental-summary bookkeeping: channel epochs as of the last
        # ACKED summary (only against that baseline may a new summary emit
        # subtree handles), and epochs captured per in-flight upload.
        self._acked_epochs: Dict[str, int] = {}
        self._upload_epochs: Dict[str, Dict[str, int]] = {}
        self.datastores: Dict[str, DataStoreRuntime] = {}
        self.pending = PendingStateManager()
        self.attached = submit_fn is not None
        self.connected = submit_fn is not None
        # client id (string) -> ordinal (join seq) interning; consistent
        # across replicas because join ops are totally ordered.
        self._ordinals: Dict[str, int] = {}
        self.client_id: Optional[str] = None  # our wire client id
        self.client_ordinal: int = -1
        self.sequence_number = 0
        self.minimum_sequence_number = 0
        self._batch: Optional[List] = None
        self.blob_manager = BlobManager()
        # Datastores created as GC roots (aliased/default stores); non-root
        # stores stay alive only while a handle route reaches them.
        self._gc_roots: List[str] = []

    # -- wiring ------------------------------------------------------------
    def attach(self, submit_fn: Callable[[str, Any], int]) -> None:
        """Attach to a delta stream. `connected` stays False until our own
        join op is sequenced — edits made before that are recorded as channel
        pendings and resubmitted at connect, carrying the real ordinal (a
        pre-join submit must never ship ordinal-derived identity)."""
        self._submit_fn = submit_fn
        self.attached = True
        for store in self.datastores.values():
            store.connect()

    def set_local_client(self, client_id: str) -> None:
        self.client_id = client_id

    def set_connected(self, connected: bool, client_id: Optional[str] = None
                      ) -> None:
        """Connection state change (reference setConnectionState). On
        reconnect: drop in-flight records and resubmit regenerated ops."""
        if client_id is not None:
            self.client_id = client_id
        was = self.connected
        self.connected = connected
        if not connected:
            self.signals_live = False
        if connected and not was:
            self._resubmit_all()
        elif was and not connected:
            # In-flight ops may still be sequenced under the old client id;
            # remember it so process() can recognize them as ours.
            self.pending.on_connection_change(self.client_id)
        self.emit("connected" if connected else "disconnected")

    # -- datastores --------------------------------------------------------
    def create_datastore(self, store_id: str,
                         root: bool = True) -> DataStoreRuntime:
        """root=True pins the store as a GC root (the reference's
        root/aliased data stores); root=False stores survive only while
        some channel value holds a handle to them."""
        if store_id in self.datastores:
            raise ValueError(f"duplicate datastore id {store_id!r}")
        store = DataStoreRuntime(store_id, self, self.registry)
        self.datastores[store_id] = store
        if root:
            self._gc_roots.append(f"/{store_id}")
        if self.attached:
            # Live creation: replicate the (empty) store; its channels each
            # ship their own attach op as they are created.
            info = {"id": store_id, "root": root}
            self._pending_store_attach[store_id] = info
            if self.connected:
                self._send({"attachStore": info})
        return store

    def get_datastore(self, store_id: str) -> DataStoreRuntime:
        return self.datastores[store_id]

    # -- submission --------------------------------------------------------
    def submit_datastore_op(self, store_id: str, envelope: dict) -> None:
        if self.read_only:
            raise PermissionError(
                "read-only container: local edits cannot be submitted "
                "(and would permanently shadow remote state if applied)")
        if not (self.attached and self.connected):
            return
        contents = {"address": store_id, "contents": envelope}
        if self._batch is not None:
            self._batch.append(contents)
            return
        self._send(contents)

    # -- signals (transient, unsequenced) ----------------------------------
    def submit_signal(self, signal_type: str, content: Any,
                      address: Optional[str] = None) -> None:
        """Broadcast a transient runtime signal (reference
        containerRuntime.submitSignal). `address` targets a datastore's
        signal listeners; None stays at container-runtime scope. Dropped
        silently while disconnected — signals carry no delivery guarantee."""
        if self._submit_signal_fn is None or \
                not (self.connected or self.signals_live):
            return
        try:
            self._submit_signal_fn({"address": address, "type": signal_type,
                                    "content": content})
        except (ConnectionError, OSError):
            # The socket died before the disconnect event landed: honor the
            # no-delivery-guarantee contract (drop, don't raise into app
            # code); the connection's own teardown drives reconnect.
            pass

    def process_signal(self, signal, local: bool) -> None:
        """Route an inbound SignalMessage (reference processSignal): an
        addressed envelope goes to the datastore; unaddressed signals emit
        at runtime scope as ("signal", type, content, local, client_id)."""
        envelope = signal.content
        if not isinstance(envelope, dict):
            return  # malformed/foreign signal: ignore, never crash the pump
        address = envelope.get("address")
        if address is not None:
            store = self.datastores.get(address)
            if store is not None:
                store.process_signal(envelope, local, signal.client_id)
            return
        self.emit("signal", envelope.get("type"), envelope.get("content"),
                  local, signal.client_id)

    def order_sequentially(self, callback: Callable[[], None]) -> None:
        """Batch ops submitted inside callback into one turn (reference
        orderSequentially/batching, containerRuntime.ts:1506)."""
        if self._batch is not None:
            callback()
            return
        self._batch = []
        try:
            callback()
            batch = self._batch
        finally:
            self._batch = None
        if len(batch) > 1 and self._submit_batch_fn is not None and \
                not any(len(json.dumps(c)) > self.max_op_size
                        for c in batch):
            # One wire submission -> one boxcar -> the sequencer tickets
            # the whole batch atomically (contiguous seqs, batch-marked).
            # Oversized members fall back to per-op sends (chunked ops
            # cannot ride a batch).
            self._submit_batch_fn(
                [(MessageType.OPERATION, c) for c in batch],
                before_send=lambda csn, c: self.pending.on_submit(csn, c))
            return
        for contents in batch:
            self._send(contents)

    def _send(self, contents) -> None:
        serialized = json.dumps(contents)
        if len(serialized) > self.max_op_size:
            self._send_chunked(serialized)
            return
        # Record pending BEFORE the wire push: over an in-process service
        # the sequenced ack can arrive synchronously inside the send.
        self._submit_fn(
            MessageType.OPERATION, contents,
            before_send=lambda csn: self.pending.on_submit(csn, contents))

    # Per-chunk envelope + framing headroom: each CHUNKED_OP message
    # (payload + chunkId/totalChunks + message fields) must itself fit
    # under the service's op-size limit.
    CHUNK_ENVELOPE_HEADROOM = 512

    def _send_chunked(self, serialized: str) -> None:
        """Split one oversized op into CHUNKED_OP messages; receivers
        reassemble per client and apply on the final chunk."""
        size = max(1, self.max_op_size - self.CHUNK_ENVELOPE_HEADROOM)
        pieces = [serialized[i:i + size]
                  for i in range(0, len(serialized), size)]
        total = len(pieces)
        for index, piece in enumerate(pieces, start=1):
            chunk = {"chunkId": index, "totalChunks": total,
                     "contents": piece}
            self._submit_fn(
                MessageType.CHUNKED_OP, chunk,
                before_send=lambda csn, c=chunk: self.pending.on_submit(csn, c))

    def _resubmit_all(self) -> None:
        self.pending.drain()
        for info in list(self._pending_store_attach.values()):
            self._send({"attachStore": info})

        def replay() -> None:
            for store_id, store in self.datastores.items():
                for envelope in store.resubmit_pending():
                    self.submit_datastore_op(store_id, envelope)
        # Channels regenerate pending ops without their original batch
        # grouping, so resubmit the WHOLE replay as one batch: at least as
        # atomic as the original groups (no foreign op interleaves, no
        # receiver yields mid-replay).
        self.order_sequentially(replay)

    # -- inbound -----------------------------------------------------------
    def process(self, message: SequencedDocumentMessage) -> None:
        """Apply one sequenced message (containerRuntime.ts:1002)."""
        self.sequence_number = message.sequence_number
        self.minimum_sequence_number = message.minimum_sequence_number
        mtype = message.type
        if mtype == MessageType.CLIENT_JOIN:
            data = message.data
            detail = json.loads(data) if isinstance(data, str) else \
                (message.contents or {})
            joined = detail.get("clientId")
            self._ordinals[joined] = message.sequence_number
            if joined == self.client_id:
                self.client_ordinal = message.sequence_number
                self._on_self_join()
            return
        if mtype == MessageType.CLIENT_LEAVE:
            data = message.data
            detail = json.loads(data) if isinstance(data, str) else \
                (message.contents or {})
            left = detail if isinstance(detail, str) else detail.get("clientId")
            self._chunk_buffers.pop(left, None)  # abandon partial chunks
            ordinal = self._ordinals.pop(left, None)
            if ordinal is not None:
                # Crash-safe lease release etc. (ConsensusQueue.client_left).
                for store in self.datastores.values():
                    for channel in store.channels.values():
                        hook = getattr(channel, "client_left", None)
                        if hook:
                            hook(ordinal)
            return
        if mtype not in (MessageType.OPERATION, MessageType.CHUNKED_OP):
            return
        local = (message.client_id == self.client_id
                 and self.client_id is not None)
        if local:
            self.pending.on_local_ack(message.client_sequence_number)
        elif message.client_id is not None:
            # An op of ours sequenced under a previous connection's id:
            # ack it instead of double-applying (remote now + resubmit later).
            if self.pending.try_prior_ack(
                    message.client_id, message.client_sequence_number):
                local = True
        contents = message.contents
        if mtype == MessageType.CHUNKED_OP:
            # Reassemble per sending client; only the final chunk applies
            # (reference processRemoteChunkedMessage).
            buf = self._chunk_buffers.setdefault(message.client_id, [])
            buf.append(contents["contents"])
            if contents["chunkId"] < contents["totalChunks"]:
                return
            del self._chunk_buffers[message.client_id]
            contents = json.loads("".join(buf))
        if "attachStore" in contents:
            info = contents["attachStore"]
            if local:
                self._pending_store_attach.pop(info["id"], None)
            elif info["id"] not in self.datastores:
                store = DataStoreRuntime(info["id"], self, self.registry)
                self.datastores[info["id"]] = store
                if info.get("root"):
                    self._gc_roots.append(f"/{info['id']}")
            return
        store = self.datastores[contents["address"]]
        ordinal = self._ordinals.get(message.client_id, -1)
        store.process(contents["contents"], local, message.sequence_number,
                      message.reference_sequence_number, ordinal,
                      message.minimum_sequence_number)
        self.emit("op", message, local)

    # -- device bulk catch-up routing (mergetree/catchup.py) ---------------
    def bulk_route(self, store_id, channel_id, client_id):
        """(store, channel) key when this message can ride a device bulk
        run: the channel exists, supports bulk apply, and the sender's
        quorum ordinal is known (merge-tree perspectives are ordinals)."""
        store = self.datastores.get(store_id)
        if store is None:
            return None
        channel = store.channels.get(channel_id)
        if channel is None or not hasattr(channel, "process_bulk_core"):
            return None
        if self._ordinals.get(client_id, -1) < 0:
            return None
        return (store_id, channel_id)

    def process_channel_bulk(self, messages) -> None:
        """Apply a run of remote OPERATION messages for one channel in one
        device pass. Raises mergetree.catchup.Unmodelable or ValueError
        (channel state untouched) to request the scalar fallback."""
        first = messages[0].contents
        store = self.datastores[first["address"]]
        channel = store.channels[first["contents"]["address"]]
        batch = []
        for m in messages:
            batch.append((m.contents["contents"]["contents"],
                          m.sequence_number, m.reference_sequence_number,
                          self._ordinals[m.client_id],
                          m.minimum_sequence_number))
        channel.process_bulk_core(batch)
        # The bulk path bypasses SharedObject.process, which is where
        # change_epoch normally bumps — an incremental summary after
        # catch-up must NOT emit a handle for this channel (that would
        # durably persist the pre-catch-up content).
        channel.change_epoch += 1

    def _on_self_join(self) -> None:
        """Adopt our quorum-assigned ordinal in every channel's perspective
        math (merge-tree clients track ints, not wire ids), then go
        connected — which resubmits any pre-join pendings."""
        for store in self.datastores.values():
            for channel in store.channels.values():
                adopt = getattr(channel, "adopt_client_ordinal", None)
                if adopt:
                    adopt(self.client_ordinal)
        self.set_connected(True)

    # -- summary / load ----------------------------------------------------
    def all_channel_epochs(self) -> Dict[str, int]:
        epochs: Dict[str, int] = {}
        for store in self.datastores.values():
            epochs.update(store.channel_epochs())
        return epochs

    def record_upload(self, handle: str,
                      epochs: Optional[Dict[str, int]] = None) -> None:
        """Remember the epochs a just-uploaded summary serialized; they
        become the acked baseline if/when that summary is acked. Callers
        pass epochs captured BEFORE assembly: an op applied mid-upload
        bumps past the captured value, so that channel re-uploads next
        time (the safe direction) instead of being wrongly marked
        durable."""
        self._upload_epochs[handle] = (
            epochs if epochs is not None else self.all_channel_epochs())

    def on_summary_ack(self, handle: Optional[str]) -> None:
        if handle in self._upload_epochs:
            self._acked_epochs = self._upload_epochs.pop(handle)
            self._upload_epochs.clear()  # older proposals are dead
        else:
            # ANOTHER client's summary became the parent: our epoch
            # baseline does not describe its tree, so the next summary
            # must be full — emitting handles against epochs we never
            # uploaded could alias stale content.
            self._acked_epochs = {}
            self._upload_epochs.clear()

    def baseline_epochs(self) -> None:
        """The current state IS durable (attach upload or fresh load):
        everything may summarize incrementally until it changes."""
        self._acked_epochs = self.all_channel_epochs()

    def summarize(self, incremental: bool = False) -> SummaryTree:
        from ..protocol.summary import SummaryHandle
        gc = self.run_gc()
        tree = SummaryTree()
        stores = tree.add_tree(".dataStores")
        for store_id, store in sorted(self.datastores.items()):
            eps = store.channel_epochs()
            acked_keys = {k for k in self._acked_epochs
                          if k.startswith(f"{store_id}/")}
            if incremental and eps and set(eps) == acked_keys and all(
                    self._acked_epochs.get(k) == v for k, v in eps.items()):
                # Whole datastore unchanged since the acked baseline: ONE
                # handle for its entire subtree (containerRuntime.ts
                # trackState at datastore granularity).
                stores.entries[store_id] = SummaryHandle("/")
            else:
                stores.entries[store_id] = store.summarize(
                    incremental=incremental,
                    acked_epochs=self._acked_epochs)
        if len(self.blob_manager):
            tree.entries[".blobs"] = self.blob_manager.summarize()
        tree.add_blob(".metadata", json.dumps({
            "sequenceNumber": self.sequence_number,
            "ordinals": self._ordinals,
            "gcRoots": self._gc_roots,
            # Mark pass result rides the summary (reference: GC runs inside
            # summarize and stamps unreferenced nodes, garbageCollector.ts).
            "unreferenced": gc.unreferenced,
        }))
        return tree

    def load(self, tree: SummaryTree) -> None:
        meta = json.loads(tree.entries[".metadata"].content)
        self.sequence_number = meta.get("sequenceNumber", 0)
        self._ordinals = {k: int(v) for k, v in
                          meta.get("ordinals", {}).items()}
        self._gc_roots = list(meta.get("gcRoots", []))
        for store_id, sub in tree.entries[".dataStores"].entries.items():
            store = DataStoreRuntime(store_id, self, self.registry)
            self.datastores[store_id] = store
            store.load(sub)
        if ".blobs" in tree.entries:
            self.blob_manager.load(tree.entries[".blobs"])

    # -- GC ----------------------------------------------------------------
    def get_gc_data(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for store in self.datastores.values():
            out.update(store.get_gc_data())
        for blob_id in self.blob_manager.node_ids():
            out[blob_id] = []  # blobs are leaves
        return out

    def run_gc(self) -> GCResult:
        return run_garbage_collection(self.get_gc_data(), self._gc_roots)
