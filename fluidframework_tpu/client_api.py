"""Legacy `Document` facade.

Capability parity with reference packages/runtime/client-api/src (662 LoC,
`document.ts`): the old flat API from before the aqueduct era — one
Document object wrapping a container, with a root SharedDirectory and
typed `create*` helpers. Kept for the same reason the reference keeps it:
existing callers and tools (e.g. replay pipelines) speak this shape.
"""

from __future__ import annotations

import itertools
from typing import Optional

from .dds.cell import SharedCell
from .dds.counter import SharedCounter
from .dds.directory import SharedDirectory
from .dds.ink import Ink
from .dds.map import SharedMap
from .dds.matrix import SharedMatrix
from .dds.sequence import (SharedNumberSequence, SharedObjectSequence,
                           SharedString)
from .loader.container import Container, Loader
from .loader.drivers.base import IDocumentServiceFactory

_uid = itertools.count(1)

ROOT_STORE = "client-api"
ROOT_CHANNEL = "root"


class Document:
    """The legacy facade. Events pass through from the container."""

    def __init__(self, container: Container, existing: bool):
        self.container = container
        self.existing = existing
        self.runtime = container.runtime
        self._store = (container.runtime.get_datastore(ROOT_STORE)
                       if existing else None)

    # -- lifecycle ---------------------------------------------------------
    @staticmethod
    def create(document_id: str, service_factory: IDocumentServiceFactory
               ) -> "Document":
        loader = Loader(service_factory)
        container = loader.create_detached(document_id)
        store = container.runtime.create_datastore(ROOT_STORE)
        store.create_channel(ROOT_CHANNEL, SharedDirectory.TYPE)
        container.attach()
        doc = Document(container, existing=False)
        doc._store = store
        return doc

    @staticmethod
    def load(document_id: str, service_factory: IDocumentServiceFactory
             ) -> "Document":
        loader = Loader(service_factory)
        return Document(loader.resolve(document_id), existing=True)

    @property
    def id(self) -> str:
        return self.container.document_id

    @property
    def client_id(self) -> Optional[str]:
        return self.container.delta_manager.client_id

    def on(self, event: str, fn) -> None:
        self.container.on(event, fn)

    def off(self, event: str, fn) -> None:
        self.container.off(event, fn)

    def close(self) -> None:
        self.container.close()

    # -- root + creation helpers (document.ts getRoot/create*) -------------
    def get_root(self) -> SharedDirectory:
        return self._store.get_channel(ROOT_CHANNEL)

    def _create(self, dds_type: str, object_id: Optional[str]):
        object_id = object_id or f"{dds_type.rsplit('/', 1)[-1]}-{next(_uid)}"
        return self._store.create_channel(object_id, dds_type)

    def create_map(self, object_id: Optional[str] = None) -> SharedMap:
        return self._create(SharedMap.TYPE, object_id)

    def create_directory(self, object_id: Optional[str] = None
                         ) -> SharedDirectory:
        return self._create(SharedDirectory.TYPE, object_id)

    def create_string(self, object_id: Optional[str] = None) -> SharedString:
        return self._create(SharedString.TYPE, object_id)

    def create_cell(self, object_id: Optional[str] = None) -> SharedCell:
        return self._create(SharedCell.TYPE, object_id)

    def create_counter(self, object_id: Optional[str] = None) -> SharedCounter:
        return self._create(SharedCounter.TYPE, object_id)

    def create_stream(self, object_id: Optional[str] = None) -> Ink:
        # The reference's createStream returns the ink stream DDS.
        return self._create(Ink.TYPE, object_id)

    def create_matrix(self, object_id: Optional[str] = None) -> SharedMatrix:
        return self._create(SharedMatrix.TYPE, object_id)

    def create_number_sequence(self, object_id: Optional[str] = None
                               ) -> SharedNumberSequence:
        return self._create(SharedNumberSequence.TYPE, object_id)

    def create_object_sequence(self, object_id: Optional[str] = None
                               ) -> SharedObjectSequence:
        return self._create(SharedObjectSequence.TYPE, object_id)

    def get(self, object_id: str):
        """Fetch an existing channel by id (document.ts get)."""
        return self._store.get_channel(object_id)
