"""Host layer (reference layer 8: packages/hosts)."""

from .base_host import BaseHost
