"""BaseHost: application-side glue over Loader + CodeLoader.

Capability parity with reference packages/hosts/base-host/src (647 LoC:
`BaseHost.initializeContainer` / `getFluidObjectFromContainer`): a host
owns the service connection and the code registry, creates or loads
containers, and resolves URLs/paths to the data objects inside them. The
reference also reacts to quorum "code" upgrades by reloading the page;
here `on_code_change` re-resolves the container for the caller.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..loader.code_loader import CodeLoader
from ..loader.container import Container, Loader
from ..loader.drivers.base import IDocumentServiceFactory
from ..runtime.datastore_runtime import ChannelRegistry


class BaseHost:
    def __init__(self, service_factory: IDocumentServiceFactory,
                 code_loader: CodeLoader,
                 code_details: Optional[dict] = None,
                 registry: Optional[ChannelRegistry] = None):
        self.loader = Loader(service_factory, registry,
                             code_loader=code_loader,
                             code_details=code_details)

    # -- containers --------------------------------------------------------
    def initialize_container(self, document_id: str,
                             code_details: Optional[dict] = None
                             ) -> Container:
        """Create-if-absent (reference initializeContainer): load the
        document, or create + attach it with the given code details."""
        try:
            return self.loader.resolve(document_id)
        except FileNotFoundError:
            container = self.loader.create_detached(document_id, code_details)
            container.attach()
            return container

    # -- object resolution -------------------------------------------------
    def get_fluid_object(self, document_id: str, path: str = "/"):
        """Resolve a document + path to a data object (reference
        getFluidObjectFromContainer)."""
        container = self.initialize_container(document_id)
        return container.request(path)

    def on_code_change(self, container: Container,
                       reload: Callable[[Container], None]) -> None:
        """Invoke `reload` with a freshly loaded container whenever a quorum
        code upgrade is approved (the reference's page-reload path)."""
        container.on(
            "codeChanged",
            lambda details: reload(self.loader.resolve(container.document_id)))
