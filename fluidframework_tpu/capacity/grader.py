"""Capacity grading: binary-search the sustained rate the SLO holds at.

The open-loop methodology (docs/capacity.md, after the Pulsar
enterprise-scale study): capacity is NOT "ops the loop managed to push"
— it is the highest OFFERED rate at which the pipeline still meets its
SLO (admission ladder <= THROTTLE over the steady window, admitted-op
flush p99 under budget, readers adopting catch-up artifacts). The
grader probes a rate-multiplier axis with a deterministic probe
function (a fresh FleetSoak per probe in the bench; a synthetic tier in
tests), bisects the pass/fail boundary, and attributes the binding
bottleneck from the first failing sample's per-tier pressure feed.

The probe contract keeps this module generic and unit-testable with a
known-capacity synthetic tier:

    probe(rate_mult) -> {"ok": bool,
                         "pressures": {tier: float, ...},   # optional
                         ...figures...}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


def attribute_bottleneck(pressures: Dict[str, float],
                         lags: Optional[Dict[str, float]] = None
                         ) -> Tuple[Optional[str], List[Tuple[str, float]]]:
    """Name the binding tier: the argmax of the normalized pressure
    feed, with the full ranking returned for the record (ties break
    alphabetically so attribution is deterministic). When the probe
    also reports per-tier watermark lags (SoakResult.tier_lags), a
    pressure tie breaks toward the tier with the larger peak lag —
    lag is the direct consumer-side evidence, pressure the proxy."""
    if not pressures:
        return None, []
    lags = lags or {}
    ranked = sorted(pressures.items(),
                    key=lambda kv: (-kv[1], -lags.get(kv[0], 0.0), kv[0]))
    return ranked[0][0], ranked


@dataclass
class GradeSample:
    rate_mult: float
    ok: bool
    sample: dict


@dataclass
class GradeResult:
    """The graded capacity point: the highest probed multiplier that
    held the SLO, the first failing one above it, and the bottleneck
    named from the failing sample's pressures (a pipeline that never
    failed inside [lo, hi] reports ``saturated=False`` and attributes
    from the highest passing sample instead)."""

    capacity_mult: float
    saturated: bool
    bottleneck: Optional[str]
    pressure_ranking: List[Tuple[str, float]] = field(default_factory=list)
    bottleneck_lag: Optional[float] = None
    passing: Optional[GradeSample] = None
    failing: Optional[GradeSample] = None
    history: List[GradeSample] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "capacity_mult": round(self.capacity_mult, 4),
            "saturated": self.saturated,
            "bottleneck": self.bottleneck,
            # The losing tier's peak watermark lag from the attributed
            # sample (ops or records behind, per the edge) — the direct
            # consumer-side evidence beside the normalized pressure.
            "bottleneck_lag": (round(self.bottleneck_lag, 1)
                               if self.bottleneck_lag is not None
                               else None),
            "pressure_ranking": [[t, round(v, 4)]
                                 for t, v in self.pressure_ranking],
            "probes": [{"rate_mult": round(s.rate_mult, 4), "ok": s.ok}
                       for s in self.history],
        }


class CapacityGrader:
    """Bisect the SLO boundary over a rate-multiplier axis.

    probe: deterministic sample function (same mult => same verdict —
    the FleetSoak probe reseeds workload + plan per run, so this holds
    by construction). lo should comfortably pass and hi should
    comfortably fail; when lo fails the capacity is graded 0 (under
    the floor), when hi passes the range is reported unsaturated with
    capacity pinned at hi."""

    def __init__(self, probe: Callable[[float], dict],
                 lo: float = 0.25, hi: float = 2.0, iters: int = 5):
        if lo <= 0 or hi <= lo:
            raise ValueError("need 0 < lo < hi")
        self.probe = probe
        self.lo = lo
        self.hi = hi
        self.iters = iters

    def _sample(self, mult: float, history: List[GradeSample]
                ) -> GradeSample:
        out = self.probe(mult)
        s = GradeSample(rate_mult=mult, ok=bool(out.get("ok")), sample=out)
        history.append(s)
        return s

    @staticmethod
    def _attribute(sample: GradeSample):
        """Bottleneck + ranking + the named tier's watermark lag from
        one sample (probes without a lag feed cite None)."""
        lags = sample.sample.get("tier_lags") or {}
        tier, ranking = attribute_bottleneck(
            sample.sample.get("pressures", {}), lags)
        lag = lags.get(tier) if tier is not None else None
        return tier, ranking, lag

    def search(self) -> GradeResult:
        history: List[GradeSample] = []
        lo_s = self._sample(self.lo, history)
        if not lo_s.ok:
            tier, ranking, lag = self._attribute(lo_s)
            return GradeResult(capacity_mult=0.0, saturated=True,
                               bottleneck=tier, pressure_ranking=ranking,
                               bottleneck_lag=lag,
                               failing=lo_s, history=history)
        hi_s = self._sample(self.hi, history)
        if hi_s.ok:
            tier, ranking, lag = self._attribute(hi_s)
            return GradeResult(capacity_mult=self.hi, saturated=False,
                               bottleneck=tier, pressure_ranking=ranking,
                               bottleneck_lag=lag,
                               passing=hi_s, history=history)
        best_pass, first_fail = lo_s, hi_s
        for _ in range(self.iters):
            mid = (best_pass.rate_mult + first_fail.rate_mult) / 2.0
            mid_s = self._sample(mid, history)
            if mid_s.ok:
                best_pass = mid_s
            else:
                first_fail = mid_s
        tier, ranking, lag = self._attribute(first_fail)
        return GradeResult(capacity_mult=best_pass.rate_mult,
                           saturated=True, bottleneck=tier,
                           pressure_ranking=ranking, bottleneck_lag=lag,
                           passing=best_pass,
                           failing=first_fail, history=history)
