"""The fleet soak: open-loop workload against the WHOLE pipeline.

Every tier smoke grades one stage in isolation; this driver runs the
full alfred→deli→broadcast→scribe→reader pipeline AT ONCE — sharded
ingest (server/sharding.py SequencerShardSet), sharded broadcast
fan-out, scribe summarization, and the catch-up read path — under one
seeded open-loop load model (capacity/workload.py) on the VIRTUAL
clock: arrivals land at their drawn virtual times whether or not the
server keeps up, drains are budgeted per partition per tick, and wall
time never enters a graded figure (the ingest-smoke overload
discipline, docs/capacity.md).

Chaos lives INSIDE the measured envelope: plan-driven partition
crash-restarts (the sequencer rebuilds from checkpoints and replays)
and reconnect avalanches (a burst of catch-up readers + subscriber
churn) draw from the injected FaultPlan-shaped ``plan``, so run-twice
is bit-identical — ``SoakResult.fingerprint()`` digests the workload
trace, the fault trace, every document's sequenced emit stream, and
the final per-document sequence numbers.

The plan is duck-typed (``pick``/``should_reset``/``fingerprint``) so
this layer never imports testing/; callers hand in a
testing.faultinject.FaultPlan.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..mergetree.client import OP_INSERT
from ..protocol.messages import DocumentMessage, MessageType
from ..server import admission as admission_mod
from ..server.admission import AdmissionController
from ..server.lambdas.base import IPartitionLambda
from ..server.local_server import DELTAS_TOPIC, LocalServer
from ..server.partition import PartitionManager
from ..telemetry import counters as _counters
from ..telemetry import watermarks
from ..telemetry.slo import BurnRateEngine, Objective

# Watermark lag edge -> soak tier name (the attribution vocabulary of
# tier_pressures): both read-side edges fold into "readpath".
LAG_EDGE_TIER = {"ingest": "ingest", "broadcast": "broadcast",
                 "summarize": "scribe", "catchup": "readpath",
                 "adopt": "readpath"}

OK_STATES = (admission_mod.ACCEPT, admission_mod.THROTTLE)

# The soak's private admission SLO stage. The default "serving.flush"
# window holds WALL-clock samples, and on a jit host the compile-spike
# spread (p99/p50 in the thousands) would drive the ladder straight to
# DEGRADE the moment the queue un-mutes the latency term — grading
# wall noise, not load. The soak instead feeds this stage with
# VIRTUAL-time flush latencies (sequenced-tap flush vt minus submit
# vt), so ladder escalation under the grader is a pure function of the
# seeded workload. The window is cleared per run (reset_stage) so
# back-to-back grader probes in one process do not inherit residue.
FLUSH_STAGE = "capacity.flush"


@dataclass(frozen=True)
class FleetSpec:
    """Soak shape + budgets. Rates live in the WorkloadSpec; this is
    the serving side: topology, drain budgets, SLO thresholds, chaos
    cadence."""

    partitions: int = 2
    broadcaster_shards: int = 2
    broadcast_queue_limit: int = 4096
    subscribers_per_document: int = 2
    ticks: int = 48
    settle_ticks: int = 10
    drain_budget_per_partition: int = 48   # broker records per tick
    queue_limit: int = 1024
    partition_limit: Optional[int] = None
    catchup_refresh_every: int = 4         # ticks per artifact epoch
    # Chaos cadence (plan-driven; 0 disables the crash draw).
    crash_every: int = 16
    avalanche_readers: int = 24
    # SLO: ladder <= THROTTLE over the steady window, admitted-op flush
    # p99 under the virtual budget, the gate actually ABSORBING the
    # offered load (goodput: admitted/submitted over the steady window
    # — THROTTLE credit pacing sheds excess at the gate while internals
    # stay green, so without this term capacity is unbounded), and
    # (when the read tier serves artifacts) readers adopting instead of
    # tail-replaying.
    slo_flush_p99_s: float = 0.20
    slo_reader_adoption: float = 0.7
    slo_goodput: float = 0.95


@dataclass
class SoakResult:
    spec: FleetSpec
    workload_fp: str
    fault_fp: str
    duration_s: float                   # virtual
    steady_s: float                     # virtual, post-settle
    submitted: int = 0
    admitted: int = 0
    nacked: int = 0
    flushed: int = 0
    flushed_steady: int = 0
    submitted_steady: int = 0
    admitted_steady: int = 0
    flush_p50_ms: Optional[float] = None    # virtual ms, steady window
    flush_p99_ms: Optional[float] = None
    states: List[Tuple[int, str]] = field(default_factory=list)
    peak_backlog_global: int = 0
    peak_backlog_by_partition: Dict[int, int] = field(default_factory=dict)
    peak_broadcast_depth: int = 0
    peak_scribe_lag: int = 0
    partition_restarts: List[int] = field(default_factory=list)
    avalanches: int = 0
    reader_events: int = 0
    reader_events_steady: int = 0
    readers_adopted: int = 0
    readers_replayed: int = 0
    reader_residue_ops: int = 0
    refresh_epochs: int = 0
    refresh_dispatches: int = 0
    final_seq: Dict[str, int] = field(default_factory=dict)
    stream_digests: Dict[str, str] = field(default_factory=dict)
    broadcaster_shed: int = 0
    effective_partition_limit: int = 0
    wall_s: float = 0.0
    # Peak watermark lag per tier over the run (telemetry/watermarks
    # edges folded through LAG_EDGE_TIER) — the grader cites the losing
    # tier's figure — and the multi-window burn-rate verdict evaluated
    # on the virtual clock at the end of the measured envelope. Peaks
    # sample the threaded broadcast fan-out mid-flight, so they are
    # advisory citations, NOT part of the bit-identity fingerprint.
    tier_lags: Dict[str, float] = field(default_factory=dict)
    burn: Optional[dict] = None

    # -- graded figures ------------------------------------------------------
    @property
    def sustained_ops_per_sec(self) -> float:
        """Admitted-and-flushed ops per virtual second over the steady
        window — the open-loop capacity figure."""
        return self.flushed_steady / self.steady_s if self.steady_s else 0.0

    @property
    def readers_per_sec(self) -> float:
        return (self.reader_events_steady / self.steady_s
                if self.steady_s else 0.0)

    @property
    def reader_adoption(self) -> float:
        served = self.readers_adopted + self.readers_replayed
        return self.readers_adopted / served if served else 0.0

    @property
    def goodput(self) -> float:
        """Fraction of steady-window submits the gate admitted. 1.0
        when the offered load is fully absorbed; falls as THROTTLE
        credit pacing starts shedding at the gate."""
        return (self.admitted_steady / self.submitted_steady
                if self.submitted_steady else 1.0)

    def steady_states(self) -> List[str]:
        return [s for t, s in self.states
                if t >= self.spec.settle_ticks]

    # -- SLO -----------------------------------------------------------------
    def slo(self, grade_readers: bool = True) -> dict:
        """The capacity SLO: which components held over the steady
        window, and the verdict the grader binary-searches on."""
        spec = self.spec
        bad_states = sorted({s for s in self.steady_states()
                             if s not in OK_STATES})
        ladder_ok = not bad_states
        p99 = self.flush_p99_ms
        latency_ok = p99 is not None and p99 <= spec.slo_flush_p99_s * 1000.0
        served = self.readers_adopted + self.readers_replayed
        readers_graded = grade_readers and served > 0
        adoption_ok = (not readers_graded
                       or self.reader_adoption >= spec.slo_reader_adoption)
        goodput_ok = self.goodput >= spec.slo_goodput
        # Burn-rate term: a breach needs BOTH windows hot (slo.py), so
        # a run the point checks above pass cannot newly fail here —
        # sustained budget burn only confirms an overload verdict.
        burn_ok = self.burn is None or bool(self.burn.get("ok", True))
        return {
            "ladder_le_throttle": ladder_ok,
            "bad_states": bad_states,
            "flush_p99_ms": p99,
            "flush_p99_budget_ms": spec.slo_flush_p99_s * 1000.0,
            "flush_latency_ok": latency_ok,
            "goodput": round(self.goodput, 4),
            "goodput_ok": goodput_ok,
            "readers_graded": readers_graded,
            "reader_adoption": round(self.reader_adoption, 4),
            "reader_adoption_ok": adoption_ok,
            "burn_ok": burn_ok,
            "burn_attribution": (self.burn or {}).get("attribution"),
            "ok": (ladder_ok and latency_ok and goodput_ok
                   and adoption_ok and burn_ok),
        }

    # -- bottleneck attribution feed ----------------------------------------
    def tier_pressures(self) -> Dict[str, float]:
        """Normalized [~0, ~1+] pressure per tier from the run's own
        counters — the grader names the argmax as the binding
        bottleneck (docs/capacity.md)."""
        spec = self.spec
        part_limit = max(1, self.effective_partition_limit)
        peak_part = max(self.peak_backlog_by_partition.values() or [0])
        p99 = self.flush_p99_ms or 0.0
        served = self.readers_adopted + self.readers_replayed
        return {
            # The gate binds two ways: backlog filling the global queue,
            # or credit pacing shedding offered load (goodput shortfall)
            # — the larger of the two is the gate's pressure.
            "admission": max(
                self.peak_backlog_global / max(1, spec.queue_limit),
                1.0 - self.goodput),
            "ingest": peak_part / part_limit,
            "broadcast": (self.peak_broadcast_depth
                          / max(1, spec.broadcast_queue_limit)),
            "scribe": self.peak_scribe_lag / max(1, spec.queue_limit),
            "serving": p99 / max(1e-9, spec.slo_flush_p99_s * 1000.0),
            "readpath": (self.readers_replayed / served) if served else 0.0,
        }

    def fingerprint(self) -> str:
        """The run-twice bit-identity witness: every workload draw,
        every fault draw, every document's sequenced emit stream, and
        the final sequence numbers."""
        h = hashlib.sha256()
        h.update(self.workload_fp.encode())
        h.update(self.fault_fp.encode())
        for doc in sorted(self.final_seq):
            h.update(f"{doc}={self.final_seq[doc]}".encode())
            h.update(b"\x00")
            h.update(self.stream_digests.get(doc, "").encode())
            h.update(b"\x01")
        return h.hexdigest()

    def as_dict(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 4),
            "submitted": self.submitted,
            "admitted": self.admitted,
            "nacked": self.nacked,
            "flushed": self.flushed,
            "goodput": round(self.goodput, 4),
            "sustained_ops_per_sec": round(self.sustained_ops_per_sec, 1),
            "readers_per_sec": round(self.readers_per_sec, 1),
            "flush_p50_ms": self.flush_p50_ms,
            "flush_p99_ms": self.flush_p99_ms,
            "steady_states": sorted(set(self.steady_states())),
            "peak_backlog_global": self.peak_backlog_global,
            "peak_backlog_by_partition": dict(
                self.peak_backlog_by_partition),
            "peak_broadcast_depth": self.peak_broadcast_depth,
            "peak_scribe_lag": self.peak_scribe_lag,
            "partition_restarts": list(self.partition_restarts),
            "avalanches": self.avalanches,
            "readers": {"events": self.reader_events,
                        "adopted": self.readers_adopted,
                        "replayed": self.readers_replayed,
                        "adoption": round(self.reader_adoption, 4),
                        "residue_ops": self.reader_residue_ops},
            "refresh": {"epochs": self.refresh_epochs,
                        "dispatches": self.refresh_dispatches},
            "broadcaster_shed": self.broadcaster_shed,
            "slo": self.slo(),
            "tier_pressures": {k: round(v, 4)
                               for k, v in self.tier_pressures().items()},
            "tier_lags": {k: round(v, 1)
                          for k, v in sorted(self.tier_lags.items())},
            "burn": self.burn,
            "fingerprint": self.fingerprint(),
            "wall_s": round(self.wall_s, 3),
        }


class _TapLambda(IPartitionLambda):
    """Deterministic sequenced-stream tap: its own consumer group over
    the deltas topic, pumped on the soak thread, so flush virtual-times
    and per-doc stream digests never depend on broadcaster worker
    scheduling."""

    def __init__(self, ctx, sink: Callable[[str, Any], None]):
        self.ctx = ctx
        self.sink = sink

    def handler(self, message) -> None:
        value = message.value
        if isinstance(value, tuple) and len(value) == 2:
            self.sink(value[0], value[1])
        # commit() stores "processed through offset" (next read starts
        # at offset+1) — commit the message's own offset.
        self.ctx.checkpoint(message.offset)


def default_server_factory(spec: FleetSpec,
                           adm: AdmissionController) -> LocalServer:
    """The scalar-pipeline fleet core (tests; bench builds the
    TpuLocalServer equivalent): manual pump, sharded ingest + sharded
    broadcast, admission injected with the soak's virtual clock."""
    return LocalServer(
        auto_pump=False, partitions=spec.partitions, admission=adm,
        config={"broadcaster.shards": spec.broadcaster_shards,
                "broadcaster.queueLimit": spec.broadcast_queue_limit})


class FleetSoak:
    """One open-loop soak run: consumes a WorkloadModel tick by tick
    against a freshly built server, chaos plan riding along, and
    returns a SoakResult. Single-use (build a new soak per run — the
    grader probes with a fresh server per offered rate)."""

    def __init__(self, workload, spec: Optional[FleetSpec] = None,
                 plan: Optional[Any] = None,
                 server_factory: Optional[
                     Callable[[FleetSpec, AdmissionController],
                              LocalServer]] = None):
        self.workload = workload
        self.spec = spec or FleetSpec()
        self.plan = plan
        self.server_factory = server_factory or default_server_factory
        self._used = False

    # -- the run -------------------------------------------------------------
    def run(self) -> SoakResult:
        if self._used:
            raise RuntimeError("FleetSoak is single-use; build a new one")
        self._used = True
        spec = self.spec
        wspec = self.workload.spec
        tick_s = wspec.tick_s
        vnow = {"t": 0.0}
        _counters.reset_stage(FLUSH_STAGE)
        # Fresh watermark table on the soak's virtual clock: the lag
        # pipeline is itself part of the graded surface (run-twice marks
        # fold into the fingerprint), and op-ages grade in virtual
        # seconds, never wall time.
        watermarks.reset()
        watermarks.set_clock(lambda: vnow["t"])
        burn = BurnRateEngine(
            [Objective("flush_latency", 0.99,
                       "admitted-op flush latency inside the virtual "
                       "p99 budget"),
             Objective("ingest_lag", 0.95,
                       "raw-log ingest lag stays under the global "
                       "queue limit")],
            clock=lambda: vnow["t"],
            fast_window_s=8 * tick_s,
            slow_window_s=max(8 * tick_s, spec.ticks * tick_s))
        flush_gb = {"good": 0, "bad": 0}
        budget_ms = spec.slo_flush_p99_s * 1000.0
        peak_lag: Dict[str, float] = {}
        # slo_ratio=4.0: virtual latencies land on the sub-slot grid
        # (tick_s/4 resolution), so a healthy same-tick flush already
        # shows p99/p50 up to 4x as a quantization artifact. 4.0 puts
        # the budget edge at one-full-tick spread and DEGRADE at 2x
        # that — genuine queueing delay, not grid noise.
        adm = AdmissionController(
            queue_limit=spec.queue_limit,
            partition_limit=spec.partition_limit,
            recover_after_s=0.5, interval_s=tick_s / 2,
            slo_stage=FLUSH_STAGE, slo_ratio=4.0,
            clock=lambda: vnow["t"])
        server = self.server_factory(spec, adm)
        tier = server.ingest
        catchup = getattr(server, "catchup", None)
        doc_ids = [f"soak-doc-{i}" for i in range(wspec.documents)]

        result = SoakResult(
            spec=spec, workload_fp="", fault_fp="",
            duration_s=spec.ticks * tick_s,
            steady_s=(spec.ticks - spec.settle_ticks) * tick_s,
            peak_backlog_by_partition={p: 0
                                       for p in range(spec.partitions)})

        # -- the deterministic sequenced-stream tap --------------------------
        last_seq = {d: 0 for d in doc_ids}
        submit_vt: Dict[Tuple[str, str, int], float] = {}
        flushed_lat: List[Tuple[float, float]] = []  # (submit_vt, flush_vt)
        digests = {d: hashlib.sha256() for d in doc_ids}
        # Wire client ids carry a per-process random suffix; the stream
        # digest uses the soak's own stable labels so run-twice
        # fingerprints compare the STREAM, not the uuid draw.
        cid_label: Dict[Any, str] = {None: "sys"}

        def tap_sink(doc_id: str, m: Any) -> None:
            if doc_id not in digests:
                return
            seq = m.sequence_number
            last_seq[doc_id] = seq
            digests[doc_id].update(
                f"{m.type}|{cid_label.get(m.client_id, '?')}"
                f"|{m.client_sequence_number}"
                f"|{seq}|{m.minimum_sequence_number};".encode())
            key = (doc_id, m.client_id, m.client_sequence_number)
            t0 = submit_vt.pop(key, None)
            if t0 is not None:
                result.flushed += 1
                flushed_lat.append((t0, vnow["t"]))
                lat_ms = (vnow["t"] - t0) * 1000.0
                _counters.observe(FLUSH_STAGE, lat_ms)
                flush_gb["good" if lat_ms <= budget_ms else "bad"] += 1

        tap = PartitionManager(server.log, "capacity-tap", DELTAS_TOPIC,
                               lambda ctx: _TapLambda(ctx, tap_sink))

        # -- writer + subscriber connections ---------------------------------
        conns: Dict[Tuple[str, int], Any] = {}
        csn: Dict[Tuple[str, int], int] = {}
        subscribers: Dict[str, List[Any]] = {d: [] for d in doc_ids}
        for d in doc_ids:
            for w in range(wspec.writers_per_document):
                c = server.connect(d)
                conns[(d, w)] = c
                csn[(d, w)] = 0
                cid_label[c.client_id] = f"w{w}"

                def on_nack(n, d=d, w=w):
                    result.nacked += 1
                    if n.operation is not None:
                        submit_vt.pop(
                            (d, conns[(d, w)].client_id,
                             n.operation.client_sequence_number), None)

                c.on("nack", on_nack)
            for _ in range(spec.subscribers_per_document):
                subscribers[d].append(server.connect(d, {"mode": "read"}))

        downstream = [m for m in (
            getattr(server, "_broadcaster_mgr", None),
            getattr(server, "_scriptorium_mgr", None),
            getattr(server, "_copier_mgr", None),
            getattr(server, "_scribe_mgr", None)) if m is not None]

        def pump_downstream() -> None:
            for mgr in downstream:
                mgr.pump_all()
            tap.pump_all()

        def drain_all() -> None:
            while True:
                n = sum(tier.manager.pumps[p].pump()
                        for p in sorted(tier.manager.pumps))
                tier.flush_acks()
                pump_downstream()
                if n == 0:
                    break

        drain_all()  # settle the joins before the measured envelope
        adm.observe(force=True)

        # -- per-tick machinery ----------------------------------------------
        # Head-insert merge-tree op in the raw runtime envelope: the
        # device pipeline materializes lanes for it, so catch-up
        # artifacts exist for the reader leg; the scalar deli carries
        # the contents opaquely. Position 0 is always valid, so no
        # client-side length tracking enters the driver.
        mt_op = {"address": "load", "contents": {
            "address": "text", "contents": {
                "type": OP_INSERT, "pos1": 0, "seg": {"text": "x"}}}}
        disp0 = _counters.get("catchup.refresh_dispatches")
        scribe_topic = server.log.topic(DELTAS_TOPIC)

        t_settled = spec.settle_ticks * tick_s

        def submit_write(doc_idx: int, writer: int) -> None:
            d = doc_ids[doc_idx % len(doc_ids)]
            w = writer % wspec.writers_per_document
            c = conns[(d, w)]
            csn[(d, w)] += 1
            n = csn[(d, w)]
            steady = vnow["t"] >= t_settled
            result.submitted += 1
            if steady:
                result.submitted_steady += 1
            submit_vt[(d, c.client_id, n)] = vnow["t"]
            nacked0 = result.nacked
            try:
                c.submit([DocumentMessage(
                    client_sequence_number=n,
                    reference_sequence_number=last_seq[d],
                    type=MessageType.OPERATION, contents=mt_op)])
            except ConnectionError:
                submit_vt.pop((d, c.client_id, n), None)
                return
            if result.nacked == nacked0:
                result.admitted += 1
                if steady:
                    result.admitted_steady += 1

        def serve_reader(doc_idx: int, steady: bool) -> None:
            d = doc_ids[doc_idx % len(doc_ids)]
            result.reader_events += 1
            if steady:
                result.reader_events_steady += 1
            art = (catchup.get(server.tenant_id, d,
                               head_seq=last_seq[d])
                   if catchup is not None else None)
            if art is not None:
                result.readers_adopted += 1
                result.reader_residue_ops += max(
                    0, last_seq[d] - int(art["seq"]))
            else:
                result.readers_replayed += 1

        def poll_peaks() -> None:
            backlogs = tier.raw_backlog_by_partition()
            for p, b in backlogs.items():
                result.peak_backlog_by_partition[p] = max(
                    result.peak_backlog_by_partition.get(p, 0), b)
            result.peak_backlog_global = max(result.peak_backlog_global,
                                             sum(backlogs.values()))
            result.peak_broadcast_depth = max(
                result.peak_broadcast_depth, server.broadcast_queue_depth())
            lag = sum(max(0, scribe_topic.partitions[p].end_offset
                          - server.log.committed("scribe", DELTAS_TOPIC, p))
                      for p in range(spec.partitions))
            result.peak_scribe_lag = max(result.peak_scribe_lag, lag)
            # Pull-model watermark refresh (raw offsets + ticketed seqs)
            # at the same boundaries the peaks sample, then fold each
            # edge's total into the per-tier peak-lag citation.
            refresh = getattr(tier, "refresh_watermarks", None)
            if refresh is not None:
                refresh()
            for edge, per in watermarks.lags().items():
                t_name = LAG_EDGE_TIER[edge]
                peak_lag[t_name] = max(peak_lag.get(t_name, 0.0),
                                       float(sum(per.values())))

        budget = spec.drain_budget_per_partition
        wall0 = time.perf_counter()
        for t in range(spec.ticks):
            start = t * tick_s
            steady = t >= spec.settle_ticks
            plan_tick = self.workload.tick()
            # Chaos draws ride the fault plan, INSIDE the envelope.
            extra_reads = 0
            if self.plan is not None:
                if spec.crash_every and (t + 1) % spec.crash_every == 0:
                    idx = self.plan.pick(spec.partitions + 1,
                                         site="partition-crash")
                    if idx < spec.partitions:
                        tier.restart_partition(idx)
                        result.partition_restarts.append(idx)
                if spec.avalanche_readers and self.plan.should_reset():
                    result.avalanches += 1
                    extra_reads = spec.avalanche_readers
            writes, reads = plan_tick.writes, plan_tick.reads
            wi = ri = 0
            for s in range(4):
                hi = (s + 1) / 4.0
                while wi < len(writes) and writes[wi].offset < hi:
                    ev = writes[wi]
                    vnow["t"] = start + ev.offset * tick_s
                    submit_write(ev.document, ev.writer)
                    wi += 1
                while ri < len(reads) and reads[ri].offset < hi:
                    ev = reads[ri]
                    vnow["t"] = start + ev.offset * tick_s
                    serve_reader(ev.document, steady)
                    ri += 1
                vnow["t"] = start + hi * tick_s
                poll_peaks()
                for p in sorted(tier.manager.pumps):
                    tier.pump_partition(
                        p, (budget * (s + 1)) // 4 - (budget * s) // 4)
                tier.flush_acks()
                pump_downstream()
            # Avalanche reconnects land at the tick edge: churn one
            # subscriber and slam the catch-up path with a reader burst.
            if extra_reads:
                d_idx = self.plan.pick(len(doc_ids), site="avalanche-doc")
                d = doc_ids[d_idx]
                if subscribers[d]:
                    subscribers[d].pop(0).disconnect()
                    subscribers[d].append(
                        server.connect(d, {"mode": "read"}))
                for _ in range(extra_reads):
                    serve_reader(self.plan.pick(len(doc_ids),
                                                site="avalanche-read"),
                                 steady)
            vnow["t"] = start + tick_s
            if (spec.catchup_refresh_every and catchup is not None
                    and (t + 1) % spec.catchup_refresh_every == 0):
                server.refresh_catchup()
                result.refresh_epochs += 1
            adm.observe(force=True)
            result.states.append((t, adm.state))
            # Burn-rate feed, once per tick on the virtual clock: the
            # tick's flush good/bad split and whether ingest lag stayed
            # under the global queue limit.
            burn.record("flush_latency", good=flush_gb["good"],
                        bad=flush_gb["bad"])
            flush_gb["good"] = flush_gb["bad"] = 0
            ingest_lag = watermarks.total_lag("ingest")
            ok_lag = ingest_lag <= spec.queue_limit
            burn.record("ingest_lag", good=1 if ok_lag else 0,
                        bad=0 if ok_lag else 1)

        # -- converge: drain everything left, chaos off ----------------------
        drain_all()
        if catchup is not None:
            server.refresh_catchup()
            result.refresh_epochs += 1
        server.drain_broadcast(20.0)
        result.refresh_dispatches = (_counters.get(
            "catchup.refresh_dispatches") - disp0)
        result.wall_s = time.perf_counter() - wall0
        # Final watermark refresh so the exported lag surface reconciles
        # with the drained pipeline; the burn verdict is evaluated at
        # virtual end-of-run, then the table's clock goes back to wall
        # time for whoever scrapes it next.
        refresh = getattr(tier, "refresh_watermarks", None)
        if refresh is not None:
            refresh()
        for edge, per in watermarks.lags().items():
            t_name = LAG_EDGE_TIER[edge]
            peak_lag[t_name] = max(peak_lag.get(t_name, 0.0),
                                   float(sum(per.values())))
        result.tier_lags = dict(peak_lag)
        result.burn = burn.evaluate(now=vnow["t"])
        watermarks.set_clock(time.monotonic)

        # -- figures ---------------------------------------------------------
        steady_lat = sorted((f1 - f0) * 1000.0
                            for f0, f1 in flushed_lat if f0 >= t_settled)
        result.flushed_steady = len(steady_lat)
        if steady_lat:
            result.flush_p50_ms = round(
                _counters.nearest_rank(steady_lat, 0.50), 3)
            result.flush_p99_ms = round(
                _counters.nearest_rank(steady_lat, 0.99), 3)
        result.effective_partition_limit = (
            adm.partition_limit()
            or max(1, spec.queue_limit // max(1, spec.partitions)))
        # The controller observes mid-burst (the admit hot path polls
        # it between sub-slot boundaries), so its own peak sees depth
        # the boundary-sampled poll above can miss. Attribution grades
        # on the larger of the two.
        result.peak_backlog_global = max(result.peak_backlog_global,
                                         adm.peak_queue_depth)
        result.final_seq = dict(last_seq)
        result.stream_digests = {d: h.hexdigest()
                                 for d, h in digests.items()}
        result.workload_fp = self.workload.fingerprint()
        result.fault_fp = (self.plan.fingerprint()
                           if self.plan is not None else "")
        result.broadcaster_shed = sum(
            b.stats().get("shed", 0)
            for b in getattr(server, "broadcasters", []))
        # Reap the fan-out worker threads: the grader builds a fresh
        # server per probed rate and shard workers must not accumulate.
        for b in getattr(server, "broadcasters", []):
            b.close()
        return result
