"""Seeded open-loop workload models for the fleet capacity soak.

The per-tier smokes drive hand-rolled fixed-rate schedules; this module
is the ONE arrival-process implementation in the tree (docs/capacity.md).
It models load the way the Pulsar enterprise-scale study does
(PAPERS.md): OPEN LOOP — arrivals happen at their drawn virtual times
whether or not the server keeps up, so overload shows up as queue growth
and ladder escalation instead of silently stretching a closed loop's
busy time.

Determinism contract (the same one testing/faultinject.py FaultPlan
keeps): **every draw flows through one seeded RNG in a fixed call order
and is appended to ``model.trace``**, so two models with the same seed
and the same ``tick()`` call sequence produce bit-identical event
streams — ``fingerprint()`` is the witness the run-twice gates compare.

Pieces:

  OpMix             the stress rig's weighted op-kind draw (shared with
                    testing/load_test.py — the fold that keeps one op-mix
                    implementation in the tree)
  poisson_draw      Knuth Poisson sampler over an injected RNG
  ZipfPopularity    rank-frequency document popularity (hot-doc skew)
  PoissonArrivals   memoryless open-loop arrivals at a fixed mean rate
  OnOffArrivals     bursty two-state (Markov on/off) arrivals
  WorkloadModel     the composed writer/catch-up-reader mix, one RNG,
                    traced, replayable
"""

from __future__ import annotations

import bisect
import hashlib
import math
import random
from dataclasses import dataclass, field, replace
from typing import Iterator, List, Sequence, Tuple

OP_KINDS = ("map", "insert", "remove", "counter")

POISSON = "poisson"
BURSTY = "bursty"


class OpMix:
    """The load rig's op-kind mix: one weighted draw per op, consuming
    the caller's RNG exactly as ``rng.choices(kinds, weights)`` does —
    testing/load_test.py folds onto this so a profile replayed against
    either driver picks the same kinds in the same order."""

    def __init__(self, weights: Sequence[float] = (4, 3, 1, 2),
                 kinds: Sequence[str] = OP_KINDS):
        if len(weights) != len(kinds):
            raise ValueError("one weight per op kind")
        self.weights = tuple(weights)
        self.kinds = tuple(kinds)

    def draw(self, rng: random.Random) -> str:
        return rng.choices(self.kinds, weights=self.weights)[0]


def closed_loop_schedule(documents: int, clients_per_document: int,
                         ops_per_client: int
                         ) -> Iterator[Tuple[int, int, int]]:
    """The stress rig's closed-loop schedule: (doc, op, client) triples
    in the exact nesting order testing/load_test.py has always driven
    (per doc, op rounds over clients round-robin) — kept here so the
    rig and the soak share one schedule definition."""
    for d in range(documents):
        for op_index in range(ops_per_client):
            for client_index in range(clients_per_document):
                yield d, op_index, client_index


def poisson_draw(rng: random.Random, lam: float) -> int:
    """Knuth's Poisson sampler over the injected RNG (no numpy: every
    draw must ride the model's one seeded RNG). Large means split into
    <=50 chunks so exp(-lam) never underflows."""
    if lam <= 0.0:
        return 0
    k = 0
    remaining = lam
    while remaining > 0.0:
        step = min(remaining, 50.0)
        remaining -= step
        limit = math.exp(-step)
        prod = rng.random()
        while prod > limit:
            k += 1
            prod *= rng.random()
    return k


class ZipfPopularity:
    """Zipf(s) rank-frequency popularity over n documents: document i
    (0-ranked) drawn with weight 1/(i+1)^s — the hot-document skew real
    collaboration fleets show. s=0 degenerates to uniform. One
    ``rng.random()`` per draw (CDF + bisect), so the consumption is a
    fixed one-draw-per-event schedule."""

    def __init__(self, n: int, s: float = 1.0):
        if n < 1:
            raise ValueError("need at least one document")
        self.n = n
        self.s = float(s)
        weights = [1.0 / (i + 1) ** self.s for i in range(n)]
        total = sum(weights)
        acc, cdf = 0.0, []
        for w in weights:
            acc += w / total
            cdf.append(acc)
        cdf[-1] = 1.0  # guard float drift at the top bin
        self._cdf = cdf

    def draw(self, rng: random.Random) -> int:
        return bisect.bisect_left(self._cdf, rng.random())


class PoissonArrivals:
    """Memoryless open-loop arrivals: count per tick ~ Poisson(rate*dt)."""

    def __init__(self, rate_per_s: float):
        self.rate_per_s = float(rate_per_s)

    def draw_count(self, rng: random.Random, dt_s: float) -> int:
        return poisson_draw(rng, self.rate_per_s * dt_s)


class OnOffArrivals:
    """Bursty two-state arrivals (Markov-modulated Poisson): ON ticks
    arrive at ``rate_on`` (chosen so the LONG-RUN mean matches the
    requested rate), OFF ticks arrive at zero; state flips with the
    per-tick transition probabilities. One transition draw + one count
    draw per tick — fixed RNG consumption."""

    def __init__(self, rate_per_s: float, p_on_off: float = 0.18,
                 p_off_on: float = 0.30, start_on: bool = True):
        self.rate_per_s = float(rate_per_s)
        self.p_on_off = p_on_off
        self.p_off_on = p_off_on
        self.on = start_on
        # Stationary P(on) = p_off_on / (p_on_off + p_off_on); scale the
        # burst rate so the delivered mean stays the requested rate.
        duty = p_off_on / max(1e-9, (p_on_off + p_off_on))
        self.rate_on = self.rate_per_s / max(1e-9, duty)

    def draw_count(self, rng: random.Random, dt_s: float) -> int:
        flip = rng.random()
        if self.on and flip < self.p_on_off:
            self.on = False
        elif not self.on and flip < self.p_off_on:
            self.on = True
        if not self.on:
            return 0
        return poisson_draw(rng, self.rate_on * dt_s)


@dataclass(frozen=True)
class WorkloadSpec:
    """The load model: an open-loop writer stream + an open-loop
    catch-up-reader stream over a Zipf-popular document fleet."""

    documents: int = 16
    writers_per_document: int = 2
    seed: int = 0
    arrival: str = POISSON          # POISSON | BURSTY
    writer_rate_per_s: float = 800.0    # fleet-wide op submissions/s
    reader_rate_per_s: float = 200.0    # fleet-wide catch-up connects/s
    zipf_s: float = 1.0
    tick_s: float = 0.02
    op_weights: Tuple[float, ...] = (4, 3, 1, 2)

    def scaled(self, mult: float) -> "WorkloadSpec":
        """The grader's probe knob: the same model shape at ``mult``
        times the offered rate (writers and readers together)."""
        return replace(self, writer_rate_per_s=self.writer_rate_per_s * mult,
                       reader_rate_per_s=self.reader_rate_per_s * mult)


@dataclass(frozen=True)
class WriteEvent:
    offset: float          # arrival position within the tick, [0, 1)
    document: int
    writer: int


@dataclass(frozen=True)
class ReadEvent:
    offset: float
    document: int


@dataclass
class TickPlan:
    index: int
    writes: List[WriteEvent] = field(default_factory=list)
    reads: List[ReadEvent] = field(default_factory=list)


class WorkloadModel:
    """The seeded, traced event source the fleet soak consumes tick by
    tick. All draws (arrival counts, in-tick offsets, Zipf document
    picks, writer picks) ride ONE ``random.Random(seed)`` in a fixed
    per-tick order and land in ``trace`` — replaying the same seed for
    the same number of ticks is bit-identical, and ``fingerprint()``
    digests the whole decision history."""

    def __init__(self, spec: WorkloadSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.trace: List[Tuple[str, str]] = []
        self.popularity = ZipfPopularity(spec.documents, spec.zipf_s)
        if spec.arrival == BURSTY:
            self.writer_arrivals = OnOffArrivals(spec.writer_rate_per_s)
        elif spec.arrival == POISSON:
            self.writer_arrivals = PoissonArrivals(spec.writer_rate_per_s)
        else:
            raise ValueError(f"unknown arrival model {spec.arrival!r}")
        self.reader_arrivals = PoissonArrivals(spec.reader_rate_per_s)
        self.ticks = 0

    def _record(self, site: str, action: str) -> None:
        self.trace.append((site, action))

    def tick(self) -> TickPlan:
        """Draw one tick's arrivals. Writer events: (offset, Zipf doc,
        uniform writer). Reader events: (offset, Zipf doc). Sorted by
        offset with draw order as the tiebreak (sort is stable)."""
        spec = self.spec
        plan = TickPlan(index=self.ticks)
        nw = self.writer_arrivals.draw_count(self.rng, spec.tick_s)
        self._record("writes", str(nw))
        for _ in range(nw):
            ev = WriteEvent(
                offset=self.rng.random(),
                document=self.popularity.draw(self.rng),
                writer=self.rng.randrange(spec.writers_per_document))
            self._record("w", f"{ev.document}:{ev.writer}")
            plan.writes.append(ev)
        nr = self.reader_arrivals.draw_count(self.rng, spec.tick_s)
        self._record("reads", str(nr))
        for _ in range(nr):
            ev = ReadEvent(offset=self.rng.random(),
                           document=self.popularity.draw(self.rng))
            self._record("r", str(ev.document))
            plan.reads.append(ev)
        plan.writes.sort(key=lambda e: e.offset)
        plan.reads.sort(key=lambda e: e.offset)
        self.ticks += 1
        return plan

    def fingerprint(self) -> str:
        """Stable digest of every draw made so far (the FaultPlan
        idiom) — the replayability witness."""
        h = hashlib.sha256()
        for site, action in self.trace:
            h.update(site.encode())
            h.update(b"\x00")
            h.update(action.encode())
            h.update(b"\x01")
        return h.hexdigest()
