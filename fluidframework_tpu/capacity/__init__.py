"""Fleet-scale capacity soak: seeded open-loop workload models, the
whole-pipeline virtual-clock soak driver, and the SLO capacity grader
(docs/capacity.md; `make e2e-smoke` is the graded entry point)."""

from .fleet import FleetSoak, FleetSpec, SoakResult  # noqa: F401
from .grader import (  # noqa: F401
    CapacityGrader,
    GradeResult,
    GradeSample,
    attribute_bottleneck,
)
from .workload import (  # noqa: F401
    BURSTY,
    POISSON,
    OnOffArrivals,
    OpMix,
    PoissonArrivals,
    TickPlan,
    WorkloadModel,
    WorkloadSpec,
    ZipfPopularity,
    closed_loop_schedule,
    poisson_draw,
)
