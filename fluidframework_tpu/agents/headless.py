"""Headless agent runner: server-side containers hosting agents.

Capability parity with reference server/headless-agent (572 LoC: launches
Fluid containers in headless Chromium via puppeteer so agents — snapshot,
intelligence, translation — run server-side without a user): here agents
are plain Python; the runner loads real containers through a loader,
wires agent factories onto them, and tears them down on request. The
Foreman lambda can dispatch "help" tasks straight into a runner
(reference: foreman assigns tasks to registered headless workers).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..loader.container import Container, Loader


class _RunningDocument:
    def __init__(self, container: Container):
        self.container = container
        self.agents: List[Any] = []


class HeadlessAgentRunner:
    """`launch(doc_id, [agent_factory...])`: load the container and start
    one agent per factory. An agent factory is `Container -> agent` where
    the agent may expose start()/stop()."""

    def __init__(self, loader: Loader, worker_id: str = "headless-1"):
        self.loader = loader
        self.worker_id = worker_id
        self.documents: Dict[str, _RunningDocument] = {}

    # -- lifecycle ---------------------------------------------------------
    def launch(self, document_id: str,
               agent_factories: List[Callable[[Container], Any]]
               ) -> Container:
        if document_id in self.documents:
            raise ValueError(f"already running {document_id!r}")
        container = self.loader.resolve(document_id)
        running = _RunningDocument(container)
        for factory in agent_factories:
            agent = factory(container)
            start = getattr(agent, "start", None)
            if start:
                start()
            running.agents.append(agent)
        self.documents[document_id] = running
        return container

    def close(self, document_id: str) -> None:
        running = self.documents.pop(document_id, None)
        if running is None:
            return
        for agent in running.agents:
            stop = getattr(agent, "stop", None)
            if stop:
                stop()
        running.container.close()

    def close_all(self) -> None:
        for doc_id in list(self.documents):
            self.close(doc_id)

    def running(self) -> List[str]:
        return list(self.documents)

    # -- foreman integration ----------------------------------------------
    def register_with_foreman(self, foreman,
                              agent_factories: List[Callable[[Container],
                                                             Any]]) -> None:
        """Register as a foreman worker: dispatched help tasks launch the
        named document with this runner's agent set (reference: headless
        agents register for snapshot/intel help messages)."""

        def dispatch(task: dict) -> None:
            doc_id = task.get("documentId")
            if doc_id and doc_id not in self.documents:
                self.launch(doc_id, agent_factories)
            foreman.complete_task(self.worker_id, task)

        foreman.register_worker(self.worker_id, dispatch)
