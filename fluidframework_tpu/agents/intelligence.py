"""Intelligence runner agent: background text analytics over a document.

Capability parity with reference packages/agents/intelligence-runner-agent
(457 LoC: `intelRunner.ts`, `textAnalytics.ts` — run by the agent
scheduler, writes results into an "insights" map): exactly one client in
the session wins the intelligence task via AgentScheduler; it watches the
SharedString and republishes analytics into a SharedMap all clients can
read. Providers are pluggable callables `str -> dict` (the reference calls
external translation/sentiment services; the built-ins here are
self-contained)."""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

TASK_ID = "intelligence-runner"


# -- built-in providers (textAnalytics.ts role) ---------------------------
def text_analytics(text: str) -> dict:
    words = re.findall(r"[\w']+", text)
    sentences = [s for s in re.split(r"[.!?]+", text) if s.strip()]
    return {
        "charCount": len(text),
        "wordCount": len(words),
        "sentenceCount": len(sentences),
        "avgWordLength": (sum(map(len, words)) / len(words)) if words else 0.0,
    }


_POSITIVE = frozenset("good great excellent love happy wonderful best "
                      "fantastic amazing nice".split())
_NEGATIVE = frozenset("bad terrible awful hate sad worst horrible poor "
                      "wrong broken".split())


def sentiment(text: str) -> dict:
    words = [w.lower() for w in re.findall(r"[\w']+", text)]
    pos = sum(w in _POSITIVE for w in words)
    neg = sum(w in _NEGATIVE for w in words)
    score = (pos - neg) / max(1, pos + neg)
    return {"positive": pos, "negative": neg, "score": score}


_STOPWORDS = frozenset("the a an and or of to in is are was were be on at "
                       "it this that with for as by from".split())


def key_phrases(text: str, top: int = 5) -> dict:
    counts: Dict[str, int] = {}
    for word in re.findall(r"[\w']+", text.lower()):
        if word not in _STOPWORDS and len(word) > 2:
            counts[word] = counts.get(word, 0) + 1
    ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
    return {"phrases": [w for w, _ in ranked]}


DEFAULT_PROVIDERS: Dict[str, Callable[[str], dict]] = {
    "textAnalytics": text_analytics,
    "sentiment": sentiment,
    "keyPhrases": key_phrases,
}


class IntelligenceRunner:
    """Watches a SharedString; when this client holds the intelligence task,
    recomputes provider outputs every `batch_size` edits (the reference
    batches op-triggered runs the same way) into the insights map."""

    def __init__(self, scheduler, text, insights,
                 providers: Optional[Dict[str, Callable[[str], dict]]] = None,
                 batch_size: int = 1):
        self.scheduler = scheduler
        self.text = text
        self.insights = insights
        self.providers = dict(providers or DEFAULT_PROVIDERS)
        self.batch_size = batch_size
        self.runs = 0
        self._edits_since_run = 0
        self._started = False

    def start(self) -> None:
        """Volunteer for the task; the winner begins analyzing."""
        if not self._started:
            self._started = True
            self.text.on("sequenceDelta", self._on_delta)
        self.scheduler.pick(TASK_ID, self._run_once)

    @property
    def is_runner(self) -> bool:
        return self.scheduler.picked(TASK_ID)

    def stop(self) -> None:
        self.scheduler.release(TASK_ID)

    # -- internals ---------------------------------------------------------
    def _on_delta(self, *_args) -> None:
        if not self.is_runner:
            return
        self._edits_since_run += 1
        if self._edits_since_run >= self.batch_size:
            self._run_once()

    def _run_once(self) -> None:
        self._edits_since_run = 0
        self.runs += 1
        content = self.text.get_text()
        for name, provider in self.providers.items():
            self.insights.set(name, provider(content))
        self.insights.set("meta", {
            "runner": self.scheduler.container.delta_manager.client_id,
            "sequenceNumber":
                self.scheduler.container.protocol.sequence_number,
        })
