"""Agents layer (reference packages/agents + server/headless-agent)."""

from .headless import HeadlessAgentRunner
from .intelligence import (IntelligenceRunner, key_phrases, sentiment,
                           text_analytics)
