"""The TPU partition sequencer: the device pipeline on the serving path.

This is the TPU-batched IPartitionLambdaFactory of the north star (reference
services-core/src/lambdas.ts:36-73 + deli/lambda.ts:142-224): one lambda
owns a whole partition's documents, drains their boxcars into [B, T] message
tensors, and sequences them in ONE device program (ticket_kernel.
sequence_batched_strict — joins/leaves/system messages included). Admitted
merge-tree ops are then applied to device-resident per-channel segment
tables (mergetree.kernel) so the server materializes document state for
batched summarization, exactly the role Scribe's protocol replica plays in
the reference (scribe/lambda.ts:40) but vectorized across every document.

Host responsibilities are the irreducibly host-shaped ones: JSON parsing,
client-id interning, emission to the downstream topics (scriptorium/
broadcaster/scribe consume SequencedDocumentMessages unchanged), nacks,
and checkpointing.

Capacity discipline (SURVEY.md §7 hard parts 1/3): merge lanes live in
capacity buckets (one compiled program per bucket size). A lane that
overflows its bucket during apply is first zamboni-compacted and re-run;
if it still overflows it promotes to the next bucket — correct-by-recovery,
never correct-by-luck. The ticket client table grows the same way (K
doubles pre-flush when a window's join count could exceed it).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree import kernel
from ..mergetree.catchup import (
    Unmodelable,
    looks_like_merge_op,
    wire_to_host_ops,
)
from ..mergetree.host import OpBuilder, PayloadTable, extract_text
from ..mergetree.oppack import HostOp, PackedOps, pack_ops
from ..mergetree.state import DocState, make_state
from ..protocol.messages import (
    Boxcar,
    DocumentMessage,
    ITrace,
    MessageType,
    Nack,
    NackContent,
    NACK_BAD_REF_SEQ,
    SequencedDocumentMessage,
)
from . import ticket_kernel as tk
from .lambdas.base import IPartitionLambda, LambdaContext
from .log import QueuedMessage



def _bucket(n: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"window of {n} exceeds max bucket {buckets[-1]}")


# ---------------------------------------------------------------------------
# merge lanes: device-resident per-channel segment tables, capacity-bucketed
# ---------------------------------------------------------------------------

class _MergeBucket:
    """A batch of merge lanes sharing one segment capacity (one compiled
    apply program per (capacity, T-bucket) pair)."""

    def __init__(self, capacity: int, lanes: int):
        self.capacity = capacity
        self.lanes = lanes
        self.state: DocState = make_state(capacity, batch=lanes)
        self.used: List[Optional[tuple]] = [None] * lanes  # lane key or None
        self._blank_row: Optional[DocState] = None  # built lazily, reused

    def alloc(self, key: tuple) -> int:
        for i, k in enumerate(self.used):
            if k is None:
                self.used[i] = key
                return i
        # Grow the batch axis (pad with empty lanes).
        old = self.lanes
        grown = make_state(self.capacity, batch=old * 2)
        self.state = jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s) if g.ndim else s, grown, self.state)
        self.used.extend([None] * old)
        self.lanes = old * 2
        self.used[old] = key
        return old

    def free(self, lane: int) -> None:
        # Zero the row too: alloc() hands freed lanes to NEW channels, and
        # a dirty lane's stale segments would leak into the next channel's
        # materialization (summaries, catch-up seeds, LWW empty-base seed).
        self.used[lane] = None
        if self._blank_row is None:
            self._blank_row = make_state(
                self.capacity, anno_slots=self.state.anno_slots,
                overlap_slots=self.state.rem_clients.shape[-1])
        self.put_row(lane, self._blank_row)

    def row(self, lane: int) -> DocState:
        """Extract one lane as a single-doc DocState (host-side gather)."""
        return jax.tree_util.tree_map(lambda x: x[lane], self.state)

    def put_row(self, lane: int, row: DocState) -> None:
        self.state = jax.tree_util.tree_map(
            lambda b, r: b.at[lane].set(r), self.state, row)


def _repad_batch(rows: DocState, capacity: int) -> DocState:
    """Re-pad a [n, ...] sub-batch to a larger capacity (group promotion)."""
    n = rows.length.shape[0]
    base = make_state(capacity, anno_slots=rows.anno_slots,
                      overlap_slots=rows.rem_clients.shape[-1], batch=n)
    c = rows.capacity

    def widen(dst, src):
        if src.ndim <= 1:
            return src
        return dst.at[:, :c].set(src)

    return jax.tree_util.tree_map(widen, base, rows)


# Non-donating applies (kernel.apply_ops*_keep): the serving path keeps the
# pre-flush state alive until overflow recovery has cleared, so nothing is
# rebuilt on the recovery path (jax arrays are immutable; retaining the
# input is free).
_apply_keep_batched = kernel.apply_ops_batched_keep


class MergeLaneStore:
    """All merge lanes across capacity buckets + the shared payload table."""

    def __init__(self, capacities: Tuple[int, ...] = (64, 256, 1024),
                 lanes_per_bucket: int = 8,
                 t_buckets: Tuple[int, ...] = (1, 4, 16, 64, 256)):
        self.capacities = tuple(capacities)
        self.t_buckets = tuple(t_buckets)
        self.buckets = [
            _MergeBucket(c, lanes_per_bucket) for c in self.capacities]
        self.payloads = PayloadTable()
        self.builder = OpBuilder(self.payloads)
        self.where: Dict[tuple, Tuple[int, int]] = {}  # key -> (bucket, lane)
        self.opaque: set = set()  # lanes dropped (unparseable op seen)
        self.overflow_drops = 0  # lanes degraded after exhausting buckets
        self.flushes_since_compact = 0
        self.compact_every = 8

    # -- lane admission ----------------------------------------------------
    def lane_for(self, key: tuple) -> Tuple[int, int]:
        if key not in self.where:
            bucket = 0
            lane = self.buckets[bucket].alloc(key)
            self.where[key] = (bucket, lane)
        return self.where[key]

    def drop(self, key: tuple) -> None:
        """Mark a channel opaque: an op arrived the server cannot model
        (chunked/unknown payload); its device lane is abandoned."""
        if key in self.where:
            b, lane = self.where.pop(key)
            self.buckets[b].free(lane)
        self.opaque.add(key)

    def seed(self, key: tuple, entries, min_seq: int,
             current_seq: int) -> bool:
        """Bootstrap a lane from snapshot segments (a document whose
        content shipped via the attach/client summary rather than ops —
        without this, the first op addressing snapshot content finds an
        empty lane and overflows every bucket). Picks the smallest bucket
        with 2x headroom; unmodelable or oversized snapshots degrade the
        channel to opaque."""
        from ..mergetree.catchup import Unmodelable, seed_device_state
        if key in self.where or key in self.opaque:
            return key in self.where
        n = len(entries)
        last = len(self.buckets) - 1
        for b, bucket in enumerate(self.buckets):
            if n * 2 > bucket.capacity and not (b == last
                                                and n <= bucket.capacity):
                continue
            try:
                row = seed_device_state(entries, self.payloads,
                                        bucket.capacity, min_seq,
                                        current_seq)
            except (Unmodelable, ValueError):
                self.opaque.add(key)
                return False
            lane = bucket.alloc(key)
            bucket.put_row(lane, row)
            self.where[key] = (b, lane)
            return True
        self.opaque.add(key)
        return False

    # -- batched apply with overflow recovery ------------------------------
    def apply(self, streams: Dict[tuple, List[HostOp]]) -> None:
        """Apply per-lane op streams; windows longer than the largest
        T-bucket chunk into successive device passes (bulk catch-up)."""
        max_t = self.t_buckets[-1]
        while streams:
            window: Dict[tuple, List[HostOp]] = {}
            rest: Dict[tuple, List[HostOp]] = {}
            for key, ops in streams.items():
                if not ops:
                    continue
                window[key] = ops[:max_t]
                if len(ops) > max_t:
                    rest[key] = ops[max_t:]
            if not window:
                break
            self._apply_window(window)
            streams = rest

    def _apply_window(self, streams: Dict[tuple, List[HostOp]]) -> None:
        """One batched device pass per bucket; recover overflowing lanes by
        compact -> re-run -> promote."""
        per_bucket: Dict[int, Dict[int, List[HostOp]]] = {}
        for key, ops in streams.items():
            if key in self.opaque or not ops:
                continue
            b, lane = self.lane_for(key)
            per_bucket.setdefault(b, {})[lane] = ops

        for b, lane_ops in sorted(per_bucket.items()):
            bucket = self.buckets[b]
            t = _bucket(max(len(v) for v in lane_ops.values()),
                        self.t_buckets)
            streams_list = [lane_ops.get(i, []) for i in range(bucket.lanes)]
            packed = pack_ops(streams_list, steps=t)
            pre = bucket.state
            new_state = _apply_keep_batched(pre, packed)
            over = np.asarray(new_state.overflow)
            flagged = [i for i in range(bucket.lanes)
                       if over[i] and i in lane_ops]
            if flagged:
                # Adopt the clean lanes; roll flagged lanes back to their
                # pre-flush rows, then recover each individually.
                for i in flagged:
                    row = jax.tree_util.tree_map(lambda x: x[i], pre)
                    new_state = jax.tree_util.tree_map(
                        lambda bcol, r: bcol.at[i].set(r), new_state, row)
            bucket.state = new_state
            if flagged:
                # One BATCHED compact->rerun->promote per level — per-lane
                # device round-trips over a thin host link turn a 1k-lane
                # overflow burst into minutes. Lane counts pad to powers of
                # two so the compiled shapes stay bounded.
                self._recover_batch(b, {i: lane_ops[i] for i in flagged})

        self.flushes_since_compact += 1
        if self.flushes_since_compact >= self.compact_every:
            self.compact_all()

    @staticmethod
    def _pad_pow2(sub: DocState, packed: PackedOps, n: int,
                  capacity: int):
        """Pad a recovery sub-batch to a power-of-two lane count with
        empty rows + NOOP streams: the compiled (lanes, capacity, t)
        shapes stay bounded at log2 variants instead of one per distinct
        overflow-burst size."""
        tm = jax.tree_util.tree_map
        n_pad = 1 << max(n - 1, 0).bit_length()
        if n_pad == n:
            return sub, packed
        base = make_state(capacity, anno_slots=sub.anno_slots,
                          overlap_slots=sub.rem_clients.shape[-1],
                          batch=n_pad)
        sub = tm(lambda full, s: full.at[:n].set(s)
                 if getattr(full, "ndim", 0) else s, base, sub)
        packed = tm(lambda x: jnp.concatenate(
            [x, jnp.zeros((n_pad - n,) + x.shape[1:], x.dtype)], 0), packed)
        return sub, packed

    def _recover_batch(self, b: int,
                       lane_ops: Dict[int, List[HostOp]]) -> None:
        """Batched overflow recovery (the only recovery path — one lane is
        a batch of one): stack the flagged lanes' pre-flush rows into a
        sub-batch, compact + re-run them together, then group-promote the
        still-overflowing remainder upward; opaque at exhaustion."""
        tm = jax.tree_util.tree_map
        lanes = sorted(lane_ops)
        n = len(lanes)
        bucket = self.buckets[b]
        take = np.asarray(lanes)
        sub = tm(lambda x: x[take] if getattr(x, "ndim", 0) else x,
                 bucket.state)
        t = _bucket(max(len(v) for v in lane_ops.values()), self.t_buckets)
        packed = pack_ops([lane_ops[i] for i in lanes], steps=t)
        sub, packed = self._pad_pow2(sub, packed, n, bucket.capacity)
        # Attempt 1: compact in place and re-run at this capacity.
        compacted = kernel.compact_batched(sub)
        redone = _apply_keep_batched(compacted, packed)
        over = np.asarray(redone.overflow)
        carried: List[tuple] = []   # keys still overflowing
        keep: List[int] = []        # their row indices into src/packed
        for j, i in enumerate(lanes):
            if over[j]:
                carried.append(bucket.used[i])
                keep.append(j)
                bucket.free(i)
            else:
                bucket.put_row(i, tm(lambda x: x[j], redone))
        src = compacted
        for nb in range(b + 1, len(self.buckets)):
            if not carried:
                return
            n = len(keep)
            sel = np.asarray(keep)
            src = tm(lambda x: x[sel] if getattr(x, "ndim", 0) else x, src)
            packed = tm(lambda x: x[sel], packed)
            target = self.buckets[nb]
            wide = _repad_batch(src, target.capacity)
            wide, packed = self._pad_pow2(wide, packed, n, target.capacity)
            redone = _apply_keep_batched(wide, packed)
            over = np.asarray(redone.overflow)
            next_carried, next_keep = [], []
            for k, key in enumerate(carried):
                if not over[k]:
                    new_lane = target.alloc(key)
                    target.put_row(new_lane, tm(lambda x: x[k], redone))
                    self.where[key] = (nb, new_lane)
                else:
                    next_carried.append(key)
                    next_keep.append(k)
            carried, keep = next_carried, next_keep
            src = wide
        for key in carried:
            del self.where[key]
            self.opaque.add(key)
            self.overflow_drops += 1

    def compact_all(self) -> None:
        """Zamboni every bucket (reference mergeTree.ts:1422, run between
        batches so the gather cost amortizes, kernel.py design note)."""
        for bucket in self.buckets:
            if any(k is not None for k in bucket.used):
                bucket.state = kernel.compact_batched(bucket.state)
        self.flushes_since_compact = 0

    # -- batched summary extraction ----------------------------------------
    def extract_dispatch(self) -> List[tuple]:
        """Phase 1 (device, async): launch ONE extraction pass per bucket
        (mask + prefix-sum packing, kernel.extract_visible_batched). The
        returned jobs hold in-flight device arrays — jax dispatch is
        asynchronous, so the caller can keep sequencing the next window
        while these execute (the reference's pipeline-stage overlap,
        kafka-service/README.md:58-60)."""
        jobs = []
        for bucket in self.buckets:
            lanes = [(i, key) for i, key in enumerate(bucket.used)
                     if key is not None]
            if not lanes:
                continue
            packed = kernel.extract_visible_batched(bucket.state)
            jobs.append((packed, lanes, bucket.state.seq,
                         bucket.state.min_seq))
        return jobs

    def extract_assemble(self, jobs: List[tuple],
                         chunk_chars: int = 10000) -> Dict[tuple, dict]:
        """Phase 2 (host): D2H transfer + text/props assembly touching only
        the visible rows. Returns {lane_key: {"header", "chunks"}} — chunked
        snapshot shape per reference SnapshotV1 (snapshotV1.ts:33-40)."""
        from ..mergetree.host import assemble_entries, chunk_entries

        from ..mergetree.constants import SEG_MARKER

        out: Dict[tuple, dict] = {}
        for packed, lanes, seq_dev, min_seq_dev in jobs:
            packed = kernel.fetch_extracted(packed)
            seqs = np.asarray(seq_dev)
            min_seqs = np.asarray(min_seq_dev)
            for lane, key in lanes:
                entries = assemble_entries(packed, self.payloads, lane,
                                           min_seq=int(min_seqs[lane]))
                chunks = chunk_entries(entries, chunk_chars)
                total = sum(
                    (1 if e["kind"] == SEG_MARKER else len(e["text"]))
                    for e in entries if e.get("removedSeq") is None)
                out[key] = {
                    "header": {
                        "sequenceNumber": int(seqs[lane]),
                        "minimumSequenceNumber": int(min_seqs[lane]),
                        "totalLength": total,
                        "chunkCount": len(chunks),
                    },
                    "chunks": chunks,
                }
        return out

    def extract_all(self, chunk_chars: int = 10000) -> Dict[tuple, dict]:
        return self.extract_assemble(self.extract_dispatch(), chunk_chars)

    # -- queries -----------------------------------------------------------
    def text(self, key: tuple) -> Optional[str]:
        """Materialized text for a channel (None if opaque/unknown)."""
        if key not in self.where:
            return None
        b, lane = self.where[key]
        return extract_text(self.buckets[b].row(lane), self.payloads)

    def lane_count(self) -> int:
        return len(self.where)


# ---------------------------------------------------------------------------
# LWW lanes: map/cell/counter channels on device (server/lww_kernel.py)
# ---------------------------------------------------------------------------

_CELL_KEY = "\x00cell"  # SharedCell = a one-key LWW map


def looks_like_lww_op(op: Any) -> bool:
    if not isinstance(op, dict):
        return False
    t = op.get("type")
    if t in ("set", "delete"):
        # MapKernel ops always carry a pid; requiring it keeps shape-alike
        # ops from other DDSes out of the LWW lanes.
        return isinstance(op.get("key"), str) and "pid" in op
    if t == "clear":
        return "pid" in op  # ink's clear has no pid; directory's has a path
    if t == "increment":
        return "delta" in op
    return t in ("setCell", "deleteCell")


class _LwwBucket:
    """A batch of LWW lanes sharing one key-slot capacity (mirrors
    _MergeBucket: per-capacity buckets instead of one global table, so one
    hot channel cannot inflate device memory for every lane)."""

    def __init__(self, lk, capacity: int, lanes: int = 8):
        self.lk = lk
        self.capacity = capacity
        self.lanes = lanes
        self.state = lk.make_lww_state(capacity, batch=lanes)
        self.used: List[Optional[tuple]] = [None] * lanes
        self._blank_row = None  # built lazily, reused across frees

    def alloc(self, key: tuple) -> int:
        for i, k in enumerate(self.used):
            if k is None:
                self.used[i] = key
                return i
        old = self.lanes
        grown = self.lk.make_lww_state(self.capacity, batch=old * 2)
        self.state = jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s), grown, self.state)
        self.used.extend([None] * old)
        self.lanes = old * 2
        self.used[old] = key
        return old

    def free(self, lane: int) -> None:
        # Zero on free: reused lanes must not expose the previous
        # channel's keys/values (see _MergeBucket.free).
        self.used[lane] = None
        if self._blank_row is None:
            self._blank_row = self.lk.make_lww_state(self.capacity)
        self.put_row(lane, self._blank_row)

    def row(self, lane: int):
        return jax.tree_util.tree_map(lambda x: x[lane], self.state)

    def put_row(self, lane: int, row) -> None:
        self.state = jax.tree_util.tree_map(
            lambda b, r: b.at[lane].set(r), self.state, row)


class LwwLaneStore:
    """Device-resident LWW channel lanes + host key/value interning: the
    map/cell/counter half of server-side materialization (mapKernel.ts:490
    remote-apply semantics, batched across channels). Lanes live in
    key-capacity buckets; a lane whose key set outgrows its bucket promotes
    to the next one and its window re-applies from the retained pre-state."""

    def __init__(self, capacities: Tuple[int, ...] = (64, 1024, 16384),
                 lanes_per_bucket: int = 8,
                 t_buckets: Tuple[int, ...] = (1, 4, 16, 64, 256)):
        from . import lww_kernel as lk

        self.lk = lk
        self.capacities = tuple(capacities)
        self.t_buckets = tuple(t_buckets)
        self.buckets = [_LwwBucket(lk, c, lanes_per_bucket)
                        for c in self.capacities]
        self.where: Dict[tuple, Tuple[int, int]] = {}
        self.opaque: set = set()  # channels dropped after bucket exhaustion
        self.overflow_drops = 0
        self.key_ids: Dict[str, int] = {}
        self.key_names: List[str] = []
        self.values: List[Any] = []  # payload refs -> raw (encoded) values
        self.windows_since_value_compact = 0
        self.value_compact_every = 64

    def intern_key(self, key: str) -> int:
        if key not in self.key_ids:
            self.key_ids[key] = len(self.key_names)
            self.key_names.append(key)
        return self.key_ids[key]

    def add_value(self, value: Any) -> int:
        self.values.append(value)
        return len(self.values) - 1

    def lane_for(self, key: tuple) -> Tuple[int, int]:
        if key not in self.where:
            lane = self.buckets[0].alloc(key)
            self.where[key] = (0, lane)
        return self.where[key]

    def seed(self, key: tuple, kind: str, header: Any) -> bool:
        """Bootstrap a lane from a summary header (map entries / cell
        value / counter accumulator) as synthetic seq-0 ops — any real op
        (seq >= 1) wins LWW over the seeded base."""
        lk = self.lk
        if key in self.where:
            return True
        if key in self.opaque:
            return False
        ops: List[tuple] = []
        try:
            if kind == "map" and isinstance(header, dict):
                for k, v in header.items():
                    ops.append((lk.LwwKind.SET, self.intern_key(k),
                                self.add_value(v), 0, 0))
            elif kind == "cell" and isinstance(header, dict):
                if header.get("hasValue"):
                    ops.append((lk.LwwKind.SET, self.intern_key(_CELL_KEY),
                                self.add_value(header.get("value")), 0, 0))
            elif kind == "counter" and isinstance(header, dict):
                delta = int(header.get("value", 0))
                if not (-2**31 <= delta < 2**31):
                    raise ValueError("counter base exceeds int32")
                if delta:
                    ops.append((lk.LwwKind.ADD, -1, -1, delta, 0))
            else:
                raise ValueError(f"unseedable header kind {kind!r}")
        except (ValueError, TypeError):
            # Unrepresentable base: materializing live ops over an EMPTY
            # base would serve wrong state — degrade to opaque instead.
            self.opaque.add(key)
            return False
        if ops:
            self.apply({key: ops})
            if key in self.opaque:
                return False  # oversized snapshot: degraded, not fatal
        else:
            self.lane_for(key)  # empty base: allocate so snapshots report
        return True

    def wire_to_op(self, op: dict, seq: int) -> tuple:
        """(kind, key_id, val_id, delta, seq) for one sequenced wire op.
        Raises Unmodelable (never anything else) for content the kernel
        cannot represent — a malformed op must not crash-loop the
        partition (flush aborts before checkpointing, replay redelivers)."""
        lk = self.lk
        t = op.get("type")
        try:
            if t == "set":
                return (lk.LwwKind.SET, self.intern_key(op["key"]),
                        self.add_value(op.get("value")), 0, seq)
            if t == "delete":
                return (lk.LwwKind.DELETE, self.intern_key(op["key"]), -1,
                        0, seq)
            if t == "clear":
                return (lk.LwwKind.CLEAR, -1, -1, 0, seq)
            if t == "setCell":
                return (lk.LwwKind.SET, self.intern_key(_CELL_KEY),
                        self.add_value(op.get("value")), 0, seq)
            if t == "deleteCell":
                return (lk.LwwKind.DELETE, self.intern_key(_CELL_KEY), -1,
                        0, seq)
            if t == "increment":
                delta = int(op["delta"])
                if not (-2**31 <= delta < 2**31):
                    raise Unmodelable("increment delta exceeds int32")
                return (lk.LwwKind.ADD, -1, -1, delta, seq)
        except Unmodelable:
            raise
        except Exception as err:  # noqa: BLE001 — malformed wire content
            raise Unmodelable(f"malformed lww op: {err}") from err
        raise Unmodelable(f"unknown lww op {t!r}")

    def apply(self, streams: Dict[tuple, List[tuple]]) -> None:
        """streams: lane_key -> [(kind, key_id, val_id, delta, seq)].
        Windows chunk to the largest T bucket."""
        max_t = self.t_buckets[-1]
        while streams:
            window = {k: v[:max_t] for k, v in streams.items() if v}
            streams = {k: v[max_t:] for k, v in streams.items()
                       if len(v) > max_t}
            if window:
                self._apply_window(window)
        self.windows_since_value_compact += 1
        if self.windows_since_value_compact >= self.value_compact_every:
            self.compact_values()

    def _pack(self, lanes_count: int, window_lanes: Dict[int, List[tuple]],
              t: int):
        cols = {f: np.zeros((lanes_count, t), np.int32)
                for f in ("kind", "key", "val", "delta", "seq")}
        for lane, ops in window_lanes.items():
            for i, (kind, kid, vid, delta, seq) in enumerate(ops):
                cols["kind"][lane, i] = kind
                cols["key"][lane, i] = kid
                cols["val"][lane, i] = vid
                cols["delta"][lane, i] = delta
                cols["seq"][lane, i] = seq
        return self.lk.LwwOps(**{f: jnp.asarray(cols[f]) for f in cols})

    def _apply_window(self, window: Dict[tuple, List[tuple]]) -> None:
        per_bucket: Dict[int, Dict[int, List[tuple]]] = {}
        for key, ops in window.items():
            if key in self.opaque:
                continue  # degraded channel: never re-admit
            b, lane = self.lane_for(key)
            per_bucket.setdefault(b, {})[lane] = ops
        for b, lane_ops in sorted(per_bucket.items()):
            bucket = self.buckets[b]
            t = _bucket(max(len(v) for v in lane_ops.values()),
                        self.t_buckets)
            ops_dev = self._pack(bucket.lanes, lane_ops, t)
            pre = bucket.state
            new = self.lk.apply_lww_batched(pre, ops_dev)
            over = np.asarray(new.overflow)
            flagged = [i for i in range(bucket.lanes)
                       if over[i] and i in lane_ops]
            if flagged:
                for i in flagged:
                    row = jax.tree_util.tree_map(lambda x: x[i], pre)
                    new = jax.tree_util.tree_map(
                        lambda bcol, r: bcol.at[i].set(r), new, row)
            bucket.state = new
            for i in flagged:
                self._promote(b, i, lane_ops[i], t)

    def _promote(self, b: int, lane: int, ops: List[tuple], t: int) -> None:
        """Overflowed lane: move to the next capacity bucket and re-apply
        its window from the retained pre-state row."""
        key = self.buckets[b].used[lane]
        row = self.buckets[b].row(lane)
        self.buckets[b].free(lane)
        for nb in range(b + 1, len(self.buckets)):
            target = self.buckets[nb]
            wide = self.lk.grow_lane_capacity(
                jax.tree_util.tree_map(lambda x: x[None], row),
                target.capacity)
            ops_dev = self._pack(1, {0: ops}, t)
            redone = self.lk.apply_lww_batched(wide, ops_dev)
            if not bool(np.asarray(redone.overflow)[0]):
                new_lane = target.alloc(key)
                target.put_row(new_lane, jax.tree_util.tree_map(
                    lambda x: x[0], redone))
                self.where[key] = (nb, new_lane)
                return
            row = jax.tree_util.tree_map(lambda x: x[0], wide)
        # Exhausted every key-capacity bucket: degrade this ONE channel to
        # opaque (no server-side materialization) instead of crashing the
        # pump — same discipline as the merge lanes, and it must hold for
        # client-authored summary seeds too (a crash here would loop on
        # every restart re-probe of the same stored summary).
        del self.where[key]
        self.opaque.add(key)
        self.overflow_drops += 1

    def compact_values(self) -> None:
        """Reclaim unreferenced payloads: memory must track LIVE state, not
        total op count (the merge side's zamboni analog for values)."""
        referenced: set = set()
        for bucket in self.buckets:
            if any(k is not None for k in bucket.used):
                vals = np.asarray(bucket.state.val)
                referenced.update(int(v) for v in np.unique(vals) if v >= 0)
        remap = {old: new for new, old in enumerate(sorted(referenced))}
        self.values = [self.values[old] for old in sorted(referenced)]
        for bucket in self.buckets:
            if not any(k is not None for k in bucket.used):
                continue
            vals = np.asarray(bucket.state.val)
            out = np.full_like(vals, -1)
            for old, new in remap.items():
                out[vals == old] = new
            bucket.state = bucket.state._replace(val=jnp.asarray(out))
        self.windows_since_value_compact = 0

    # -- reads (tests / snapshots) -----------------------------------------
    def snapshot(self, lane_key: tuple) -> Optional[dict]:
        """Entries hold WIRE-ENCODED values (handles stay in their encoded
        dict form): the server has no runtime to bind live handles to —
        clients decode at load, exactly as they do for ops."""
        if lane_key not in self.where:
            return None
        b, lane = self.where[lane_key]
        state = self.buckets[b].state
        keys = np.asarray(state.key[lane])
        vals = np.asarray(state.val[lane])
        entries = {}
        for kid, vid in zip(keys, vals):
            if int(kid) >= 0:
                entries[self.key_names[int(kid)]] = (
                    self.values[int(vid)] if int(vid) >= 0 else None)
        return {
            "entries": entries,
            "counter": int(np.asarray(state.counter[lane])),
            "sequenceNumber": int(np.asarray(state.last_seq[lane])),
        }


# ---------------------------------------------------------------------------
# the lambda
# ---------------------------------------------------------------------------

class _DocLane:
    """Host bookkeeping for one document's device lane."""

    def __init__(self, lane: int):
        self.lane = lane
        self.interner: Dict[str, int] = {}   # wire client id -> ordinal
        self.ordinals: Dict[int, str] = {}
        self.log_offset = -1
        self.next_ordinal = 0
        # Host mirror of live membership + last activity, for ghost-client
        # eviction (not persisted; _restore re-stamps from the device
        # client table). `evicting` dedups in-flight synthesized leaves.
        self.last_seen: Dict[str, float] = {}
        self.evicting: set = set()

    def intern(self, client_id: str) -> int:
        if client_id not in self.interner:
            self.interner[client_id] = self.next_ordinal
            self.ordinals[self.next_ordinal] = client_id
            self.next_ordinal += 1
        return self.interner[client_id]

    def dump(self) -> dict:
        return {"lane": self.lane, "logOffset": self.log_offset,
                "interner": dict(self.interner),
                "nextOrdinal": self.next_ordinal}

    @staticmethod
    def load(d: dict) -> "_DocLane":
        dl = _DocLane(d["lane"])
        dl.log_offset = d["logOffset"]
        dl.interner = {k: int(v) for k, v in d["interner"].items()}
        dl.ordinals = {v: k for k, v in dl.interner.items()}
        dl.next_ordinal = d["nextOrdinal"]
        return dl


class _Pending:
    """One parsed, not-yet-flushed message."""

    __slots__ = ("kind", "ordinal", "client_seq", "ref_seq", "msg",
                 "client_id")

    def __init__(self, kind: int, ordinal: int, client_seq: int,
                 ref_seq: int, msg: DocumentMessage,
                 client_id: Optional[str]):
        self.kind = kind
        self.ordinal = ordinal
        self.client_seq = client_seq
        self.ref_seq = ref_seq
        self.msg = msg
        self.client_id = client_id


class _SummaryProbe:
    """Parsed channel snapshots from a document's stored summary:
    sequence_number (the summary's protocol seq) + per-(store, channel)
    merge-tree seed payloads (entries, minSeq, seq) and LWW seed payloads
    (kind, header-data)."""

    def __init__(self, sequence_number: int,
                 channels: Dict[Tuple[str, str], tuple],
                 lww_channels: Optional[Dict[Tuple[str, str],
                                             tuple]] = None):
        self.sequence_number = sequence_number
        self.channels = channels
        self.lww_channels = lww_channels or {}


# Channel types the LWW lanes can seed from a summary header.
_LWW_SEED_TYPES = {
    "https://graph.microsoft.com/types/map": "map",
    "https://graph.microsoft.com/types/cell": "cell",
    "https://graph.microsoft.com/types/counter": "counter",
}


def _parse_summary_probe(tree) -> Optional[_SummaryProbe]:
    """Walk a container summary (".protocol" blob + ".app" store trees)
    and extract every chunked merge-tree channel body (sequence
    summarize_core format: header {seq, minSeq, chunkCount} + body_i)."""
    import json as _json
    proto = tree.entries.get(".protocol")
    app = tree.entries.get(".app")
    if proto is None or app is None or not hasattr(app, "entries"):
        return None
    try:
        seq = int(_json.loads(proto.content).get("sequenceNumber", 0))
    except (ValueError, TypeError, AttributeError):
        # Client-authored content: malformed protocol blob => no seeding,
        # never a pump crash.
        return None
    stores = app.entries.get(".dataStores")
    if stores is None or not hasattr(stores, "entries"):
        return None
    channels: Dict[Tuple[str, str], tuple] = {}
    lww_channels: Dict[Tuple[str, str], tuple] = {}
    for store_id, store_tree in stores.entries.items():
        if not hasattr(store_tree, "entries"):
            continue
        channel_root = store_tree.entries.get(".channels", store_tree)
        if not hasattr(channel_root, "entries"):
            continue
        for channel_id, node in channel_root.entries.items():
            if not hasattr(node, "entries") or \
                    "header" not in node.entries:
                continue
            # A malformed .attributes blob must not cost a channel its
            # merge seeding — classification just falls back to "".
            ctype = ""
            attrs = node.entries.get(".attributes")
            if attrs is not None:
                try:
                    ctype = _json.loads(attrs.content).get("type", "")
                except (ValueError, TypeError, AttributeError):
                    ctype = ""
            try:
                header = _json.loads(node.entries["header"].content)
                lww_kind = _LWW_SEED_TYPES.get(ctype)
                if lww_kind is not None:
                    lww_channels[(store_id, channel_id)] = (lww_kind,
                                                            header)
                    continue
                count = int(header.get("chunkCount", -1))
                if count < 0:
                    continue  # not a chunked merge-tree body
                entries: List[dict] = []
                for i in range(count):
                    entries.extend(_json.loads(
                        node.entries[f"body_{i}"].content))
                payload = (entries, int(header.get("minSeq", 0)),
                           int(header.get("seq", 0)))
            except (ValueError, TypeError, KeyError, AttributeError):
                continue  # malformed client channel: skip, don't crash
            channels[(store_id, channel_id)] = payload
    return _SummaryProbe(seq, channels, lww_channels)


class TpuSequencerLambda(IPartitionLambda):
    """Sequences a partition's documents on device (see module docstring).

    emit(document_id, SequencedDocumentMessage) and nack(document_id,
    client_id, Nack) have the exact DeliLambda contract, so this lambda is a
    drop-in for the scalar deli in any lambda host.
    """

    def __init__(self, context: LambdaContext,
                 emit: Callable[[str, SequencedDocumentMessage], None],
                 nack: Callable[[str, str, Nack], None],
                 lanes: int = 8, clients_capacity: int = 8,
                 checkpoints=None, deltas=None, fresh_log: bool = False,
                 materialize: bool = True,
                 merge_store: Optional[MergeLaneStore] = None,
                 t_buckets: Tuple[int, ...] = (1, 4, 16, 64, 256),
                 storage=None, client_timeout_s: float = 300.0,
                 send_system=None, config=None):
        """storage: optional callable doc_id -> SummaryTree | None (the
        historian's latest summary). Enables snapshot seeding: merge lanes
        for channels whose base content shipped in a summary bootstrap
        from it instead of overflowing on the first op.

        client_timeout_s: ghost-client eviction window (0 disables) —
        writers silent this long get a synthesized leave so they stop
        pinning the MSN (DeliLambda clientTimeout semantics). config (the
        same nconf slice DeliLambda takes) overrides it via
        deli.clientTimeoutMsec."""
        self.context = context
        self.emit = emit
        self.nack = nack
        self.checkpoints = checkpoints
        self.deltas = deltas
        self.storage = storage
        self.client_timeout_s = client_timeout_s
        if config is not None:
            configured = config.get("deli.clientTimeoutMsec", None)
            if configured is not None:
                # Override only when actually configured — an explicit
                # client_timeout_s argument survives an unrelated config.
                self.client_timeout_s = float(configured) / 1000.0
        # Eviction leaves ride the raw log when a producer is available
        # (replay-deterministic, DeliLambda semantics); fallback appends
        # to the in-memory backlog. _DocLane.evicting dedups in-flight.
        self.send_system = send_system
        # doc_id -> parsed summary probe result (None = no usable summary);
        # probed at most once per document per process.
        self._summary_probes: Dict[str, Optional["_SummaryProbe"]] = {}
        # fresh_log=True: this lambda consumes a brand-new MessageLog (a
        # multi-node takeover hands over checkpointed state, not the log);
        # checkpointed offsets index the PREVIOUS core's log and must not
        # gate replay of the new one (DeliLambda fresh_log semantics).
        self.fresh_log = fresh_log
        self.t_buckets = tuple(t_buckets)
        self.lanes = lanes
        self.k = clients_capacity
        self.tstate: tk.TicketState = tk.make_ticket_state(self.k,
                                                           batch=lanes)
        self.docs: Dict[str, _DocLane] = {}
        self.pending: Dict[str, List[_Pending]] = {}
        self.materialize = materialize
        self.merge = merge_store if merge_store is not None else \
            MergeLaneStore(t_buckets=t_buckets)
        self.lww = LwwLaneStore(t_buckets=t_buckets)
        self._pending_offset: Optional[int] = None
        self._restore()

    # -- checkpoint/restore ------------------------------------------------
    def _restore(self) -> None:
        if self.checkpoints is None:
            return
        rows = list(self.checkpoints.find(
            lambda d: d.get("kind") == "tpu-sequencer"))
        if not rows:
            return
        dump = rows[0]["state"]
        self.docs = {doc: _DocLane.load(d)
                     for doc, d in dump["docs"].items()}
        if self.fresh_log:
            for dl in self.docs.values():
                dl.log_offset = -1
        cols = dump["tstate"]
        self.lanes = len(cols["next_seq"])
        self.k = len(cols["client_ids"][0]) if cols["client_ids"] else self.k
        self.tstate = tk.TicketState(
            client_ids=jnp.asarray(np.asarray(cols["client_ids"], np.int32)),
            client_ref=jnp.asarray(np.asarray(cols["client_ref"], np.int32)),
            client_cseq=jnp.asarray(np.asarray(cols["client_cseq"],
                                               np.int32)),
            next_seq=jnp.asarray(np.asarray(cols["next_seq"], np.int32)),
            min_seq=jnp.asarray(np.asarray(cols["min_seq"], np.int32)),
            overflow=jnp.asarray(np.asarray(cols["overflow"], np.bool_)),
        )
        # Re-arm ghost eviction for members restored into the device
        # client table (last_seen is not persisted): a ghost present at
        # the crash still ages out after restart.
        now = time.time()
        ids = np.asarray(self.tstate.client_ids)
        for dl in self.docs.values():
            for ordinal in ids[dl.lane]:
                if int(ordinal) >= 0:
                    client = dl.ordinals.get(int(ordinal))
                    if client is not None:
                        dl.last_seen[client] = now
        self._rebuild_merge()

    def _probe_summary(self, doc_id: str) -> Optional[_SummaryProbe]:
        if doc_id in self._summary_probes:
            return self._summary_probes[doc_id]
        probe = None
        if self.storage is not None:
            try:
                tree = self.storage(doc_id)
            except Exception:  # noqa: BLE001 — storage miss = no seed
                tree = None
            if tree is not None:
                probe = _parse_summary_probe(tree)
        self._summary_probes[doc_id] = probe
        if probe is not None and probe.sequence_number == 0:
            # Attach summary: NOTHING can predate seq 0, so eagerly seed
            # every channel — summary-only channels (never touched by a
            # live op) materialize for server-side reads too.
            for (store, channel), payload in probe.channels.items():
                self.merge.seed((doc_id, store, channel), *payload)
            for (store, channel), payload in probe.lww_channels.items():
                self.lww.seed((doc_id, store, channel), *payload)
        return probe

    def _rebuild_merge(self) -> None:
        """Crash-restart: rebuild the device merge lanes by replaying each
        known document's sequenced deltas through the kernel in bulk — the
        server-side device catch-up path (reference deltaManager.ts:1380
        fetchMissingDeltas, applied at partition scale). Channels with a
        stored summary seed from it first, then replay only the tail past
        the summary's sequence number."""
        if self.deltas is None or not self.materialize or not self.docs:
            return
        from .lambdas.scriptorium import query_deltas
        next_seq = np.asarray(self.tstate.next_seq)
        streams: Dict[tuple, List[HostOp]] = {}
        lww_streams: Dict[tuple, List[tuple]] = {}
        for doc_id, dl in self.docs.items():
            probe = self._probe_summary(doc_id)
            seeded_before: Dict[tuple, int] = {}
            if probe is not None:
                for (store, channel), payload in probe.channels.items():
                    key = (doc_id, store, channel)
                    if self.merge.seed(key, *payload):
                        # The seeded base already reflects ops <= the
                        # summary seq for THIS channel; unseeded channels
                        # still replay from zero.
                        seeded_before[key] = probe.sequence_number
                for (store, channel), payload in \
                        probe.lww_channels.items():
                    key = (doc_id, store, channel)
                    if self.lww.seed(key, *payload):
                        seeded_before[key] = probe.sequence_number
            # Bound at the restored checkpoint's last seq: deltas persisted
            # by a flush that crashed before checkpointing will be
            # re-sequenced by the raw-log replay (same seqs, scriptorium
            # dedups) and applied to the merge lanes THEN — replaying them
            # here too would double-apply.
            last_seq = int(next_seq[dl.lane]) - 1
            for row in query_deltas(self.deltas, doc_id, 0, last_seq):
                if row.get("type") != MessageType.OPERATION or \
                        not row.get("client_id"):
                    continue
                p = _Pending(tk.MsgKind.OP, dl.intern(row["client_id"]),
                             row["client_sequence_number"],
                             row["reference_sequence_number"],
                             DocumentMessage(
                                 client_sequence_number=row[
                                     "client_sequence_number"],
                                 reference_sequence_number=row[
                                     "reference_sequence_number"],
                                 type=row["type"],
                                 contents=row.get("contents")),
                             row["client_id"])
                self._collect_channel_op(streams, lww_streams, doc_id, p,
                                         row["sequence_number"],
                                         row["minimum_sequence_number"],
                                         seeded_before=seeded_before)
        if streams:
            self.merge.apply(streams)
        if lww_streams:
            self.lww.apply(lww_streams)

    def _checkpoint(self) -> None:
        if self._pending_offset is None:
            return
        if self.checkpoints is not None:
            t = jax.tree_util.tree_map(
                lambda x: np.asarray(x).tolist(), self.tstate)
            self.checkpoints.upsert(
                lambda d: d.get("kind") == "tpu-sequencer",
                {"kind": "tpu-sequencer", "state": {
                    "docs": {doc: dl.dump() for doc, dl in self.docs.items()},
                    "tstate": t._asdict(),
                }})
        self.context.checkpoint(self._pending_offset)
        self._pending_offset = None

    # -- ingestion ---------------------------------------------------------
    def handler(self, message: QueuedMessage) -> None:
        boxcar: Boxcar = message.value
        doc_id = boxcar.document_id
        dl = self._doc(doc_id)
        if message.offset <= dl.log_offset:
            return  # checkpointed replay (deli/lambda.ts:143)
        queue = self.pending.setdefault(doc_id, [])
        for msg in boxcar.contents:
            queue.append(self._parse(dl, boxcar.client_id, msg))
        dl.log_offset = message.offset
        self._pending_offset = message.offset

    def _doc(self, doc_id: str) -> _DocLane:
        dl = self.docs.get(doc_id)
        if dl is None:
            lane = len(self.docs)
            if lane >= self.lanes:
                self._grow_lanes()
            dl = _DocLane(lane)
            self.docs[doc_id] = dl
        return dl

    def _grow_lanes(self) -> None:
        old = self.lanes
        grown = tk.make_ticket_state(self.k, batch=old * 2)
        self.tstate = jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s), grown, self.tstate)
        self.lanes = old * 2

    def _grow_clients(self) -> None:
        k2 = self.k * 2
        t = self.tstate

        def widen(col, fill):
            out = jnp.full((self.lanes, k2), fill, col.dtype)
            return out.at[:, :self.k].set(col)

        self.tstate = t._replace(
            client_ids=widen(t.client_ids, -1),
            client_ref=widen(t.client_ref, tk.INT32_MAX),
            client_cseq=widen(t.client_cseq, 0),
        )
        self.k = k2

    def _parse(self, dl: _DocLane, client_id: Optional[str],
               msg: DocumentMessage) -> _Pending:
        if msg.type == MessageType.CLIENT_JOIN:
            detail = _detail(msg)
            joining = detail.get("clientId", client_id)
            dl.last_seen[joining] = time.time()
            return _Pending(tk.MsgKind.JOIN, dl.intern(joining), 0, 0, msg,
                            None)
        if msg.type == MessageType.CLIENT_LEAVE:
            detail = _detail(msg)
            leaving = detail if isinstance(detail, str) else \
                detail.get("clientId", client_id)
            dl.last_seen.pop(leaving, None)
            dl.evicting.discard(leaving)
            return _Pending(tk.MsgKind.LEAVE, dl.intern(leaving), 0, 0, msg,
                            None)
        if client_id is None:
            return _Pending(tk.MsgKind.SYSTEM, -1, 0, 0, msg, None)
        dl.last_seen[client_id] = time.time()
        return _Pending(tk.MsgKind.OP, dl.intern(client_id),
                        msg.client_sequence_number,
                        msg.reference_sequence_number, msg, client_id)

    # -- the device flush --------------------------------------------------
    def flush(self) -> None:
        # Eviction checks only documents with activity in THIS flush —
        # the scalar deli's per-boxcar scope; a completely quiet document
        # never evicts (its idle writer had no remote ops to heartbeat
        # against either).
        self._evict_ghosts([d for d, q in self.pending.items() if q])
        # Each window consumes at least one pending message per live doc,
        # so this loop is bounded by the backlog length.
        while any(self.pending.values()):
            self._flush_window()
        self._checkpoint()

    def _evict_ghosts(self, active_docs: List[str]) -> None:
        """Synthesize leaves for writers silent past client_timeout_s
        (DeliLambda._evict_ghosts, device path). With a raw-log producer
        the leave rides the log (replay-deterministic); the fallback
        appends to the in-memory backlog so the NoClient timing and
        quorum removal stay exact either way."""
        if not self.client_timeout_s:
            return
        cutoff = time.time() - self.client_timeout_s
        for doc_id in active_docs:
            dl = self.docs.get(doc_id)
            if dl is None:
                continue
            stale = [cid for cid, ts in dl.last_seen.items()
                     if ts < cutoff and cid not in dl.evicting]
            for client_id in stale:
                leave = DocumentMessage(
                    client_sequence_number=0,
                    reference_sequence_number=-1,
                    type=MessageType.CLIENT_LEAVE,
                    data=json.dumps({"clientId": client_id,
                                     "evicted": True}))
                if self.send_system is not None:
                    dl.evicting.add(client_id)
                    self.send_system(doc_id, leave)
                else:
                    dl.last_seen.pop(client_id, None)
                    self.pending.setdefault(doc_id, []).append(_Pending(
                        tk.MsgKind.LEAVE, dl.intern(client_id), 0, 0,
                        leave, None))

    def _take_window(self) -> Dict[str, List[_Pending]]:
        """Carve the next per-doc message chunks off the backlog: at most
        max-T-bucket messages per doc, and cut immediately AFTER a LEAVE —
        so the host can interpose the NoClient message with the scalar
        deli's exact timing (deli.py CLIENT_LEAVE tail) before the doc's
        remaining messages sequence."""
        max_t = self.t_buckets[-1]
        live: Dict[str, List[_Pending]] = {}
        for doc_id, q in list(self.pending.items()):
            if not q:
                del self.pending[doc_id]
                continue
            cut = min(len(q), max_t)
            for idx in range(cut):
                if q[idx].kind == tk.MsgKind.LEAVE:
                    cut = idx + 1
                    break
            live[doc_id] = q[:cut]
            if len(q) > cut:
                self.pending[doc_id] = q[cut:]
            else:
                del self.pending[doc_id]
        return live

    def _flush_window(self) -> None:
        live = self._take_window()
        if not live:
            return
        # Pre-size the client table: joins this window + already-known
        # ordinals must fit K (grow BEFORE the kernel, so the in-kernel
        # overflow flag is a genuine invariant violation, not a sizing bug).
        need_k = max((dl.next_ordinal for dl in self.docs.values()),
                     default=0)
        while self.k < need_k:
            self._grow_clients()

        t = _bucket(max(len(q) for q in live.values()), self.t_buckets)
        b = self.lanes
        kind = np.zeros((b, t), np.int32)
        client = np.full((b, t), -1, np.int32)
        cseq = np.zeros((b, t), np.int32)
        ref = np.zeros((b, t), np.int32)
        for doc_id, queue in live.items():
            lane = self.docs[doc_id].lane
            for i, p in enumerate(queue):
                kind[lane, i] = p.kind
                client[lane, i] = p.ordinal
                cseq[lane, i] = p.client_seq
                ref[lane, i] = p.ref_seq
        raw = tk.RawOps(client=jnp.asarray(client),
                        client_seq=jnp.asarray(cseq),
                        ref_seq=jnp.asarray(ref),
                        kind=jnp.asarray(kind))
        self.tstate, ticketed = tk.sequence_batched_strict(self.tstate, raw)

        seqs = np.asarray(ticketed.seq)
        msns = np.asarray(ticketed.min_seq)
        nacked = np.asarray(ticketed.nacked)
        not_joined = np.asarray(ticketed.not_joined)
        empty_after = np.asarray(ticketed.empty_after)
        next_seq = np.asarray(self.tstate.next_seq)
        if bool(np.asarray(self.tstate.overflow).any()):
            raise RuntimeError("ticket client table overflow despite "
                               "pre-flush growth — invariant violation")

        merge_streams: Dict[tuple, List[HostOp]] = {}
        lww_streams: Dict[tuple, List[tuple]] = {}
        for doc_id, queue in live.items():
            lane = self.docs[doc_id].lane
            for i, p in enumerate(queue):
                seq = int(seqs[lane, i])
                if seq > 0:
                    sequenced = SequencedDocumentMessage.from_document_message(
                        p.msg, p.client_id, seq, int(msns[lane, i]))
                    sequenced.traces.append(ITrace.now("deli", "sequence"))
                    self.emit(doc_id, sequenced)
                    if p.kind == tk.MsgKind.OP and self.materialize:
                        self._collect_channel_op(
                            merge_streams, lww_streams, doc_id, p, seq,
                            int(msns[lane, i]))
                elif nacked[lane, i]:
                    reason = ("client not joined" if not_joined[lane, i]
                              else "refSeq below minimum sequence number")
                    self.nack(doc_id, p.client_id or "", Nack(
                        p.msg, int(next_seq[lane]) - 1,
                        NackContent(NACK_BAD_REF_SEQ, reason)))
                # NoClient with exact deli timing: windows cut right after
                # a LEAVE (_take_window), so a leave that empties the table
                # interposes NO_CLIENT before the doc's remaining backlog.
                if p.kind == tk.MsgKind.LEAVE and seq > 0 and \
                        empty_after[lane, i]:
                    self.pending.setdefault(doc_id, []).insert(0, _Pending(
                        tk.MsgKind.SYSTEM, -1, 0, 0, DocumentMessage(
                            client_sequence_number=0,
                            reference_sequence_number=int(
                                next_seq[lane]) - 1,
                            type=MessageType.NO_CLIENT), None))

        if self.materialize and merge_streams:
            self.merge.apply(merge_streams)
        if self.materialize and lww_streams:
            self.lww.apply(lww_streams)

    def _collect_channel_op(self, merge_streams: Dict[tuple, List[HostOp]],
                            lww_streams: Dict[tuple, List[tuple]],
                            doc_id: str, p: _Pending, seq: int,
                            msn: int,
                            seeded_before: Optional[Dict[tuple, int]] = None
                            ) -> None:
        """Route an admitted channel op to its device lane family:
        merge-tree ops to the segment kernel, map/cell/counter ops to the
        LWW kernel; anything else stays host-only."""
        if p.msg.type != MessageType.OPERATION:
            return
        contents = p.msg.contents
        if not isinstance(contents, dict):
            return
        envelope = contents.get("contents")
        if not isinstance(envelope, dict):
            return
        op = envelope.get("contents")
        key = (doc_id, contents.get("address"), envelope.get("address"))
        if looks_like_merge_op(op):
            if key in self.merge.opaque:
                return
            if seeded_before is not None and \
                    seq <= seeded_before.get(key, 0):
                return  # already reflected in the seeded snapshot base
            if key not in self.merge.where:
                # First op for this channel: its base content may have
                # shipped in the attach/client summary — seed the lane
                # from storage before applying ops addressed against it.
                probe = self._probe_summary(doc_id)
                if probe is not None:
                    payload = probe.channels.get((contents.get("address"),
                                                  envelope.get("address")))
                    if payload is not None and seq > probe.sequence_number:
                        self.merge.seed(key, *payload)
            try:
                ops = wire_to_host_ops(self.merge.builder, op, seq,
                                       p.ref_seq, p.ordinal, msn)
            except Unmodelable:
                self.merge.drop(key)
                return
            merge_streams.setdefault(key, []).extend(ops)
        elif looks_like_lww_op(op):
            if key in self.lww.opaque:
                return
            if seeded_before is not None and \
                    seq <= seeded_before.get(key, 0):
                return  # already reflected in the seeded snapshot base
            if key not in self.lww.where:
                probe = self._probe_summary(doc_id)
                if probe is not None:
                    payload = probe.lww_channels.get(
                        (contents.get("address"), envelope.get("address")))
                    if payload is not None and \
                            seq > probe.sequence_number:
                        self.lww.seed(key, *payload)
            try:
                lww_streams.setdefault(key, []).append(
                    self.lww.wire_to_op(op, seq))
            except Unmodelable:
                pass

    # -- batched server-side summarization ---------------------------------
    def summarize_documents(self, chunk_chars: int = 10000
                            ) -> Dict[tuple, dict]:
        """Chunked snapshots of every materialized channel — merge-tree
        lanes (one batched device extraction per capacity bucket) AND LWW
        lanes (map/cell/counter entries + counter accumulator)."""
        out = self.merge.extract_all(chunk_chars)
        for key in self.lww.where:
            snap = self.lww.snapshot(key)
            if snap is not None:
                out[key] = {
                    "header": {
                        "kind": "lww",
                        "sequenceNumber": snap["sequenceNumber"],
                    },
                    "entries": snap["entries"],
                    "counter": snap["counter"],
                }
        return out

    def summarize_documents_async(self, on_done,
                                  chunk_chars: int = 10000):
        """Pipeline-stage overlap (kafka-service/README.md:58-60): the
        device extraction is dispatched NOW (async on the accelerator
        queue); the D2H transfer + host snapshot assembly run on a worker
        thread while the caller keeps sequencing the next batch. The
        extracted device arrays are immutable, so subsequent flushes
        replacing the lane states cannot corrupt an in-flight summary."""
        import threading

        jobs = self.merge.extract_dispatch()

        def work():
            on_done(self.merge.extract_assemble(jobs, chunk_chars))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        return th

    # -- introspection (tests / summarization) -----------------------------
    def channel_text(self, doc_id: str, store: str,
                     channel: str) -> Optional[str]:
        """Server-materialized text for a channel (device state + host
        payload table) — the batched-summarization read path."""
        return self.merge.text((doc_id, store, channel))

    def channel_snapshot(self, doc_id: str, store: str,
                         channel: str) -> Optional[dict]:
        """Server-materialized LWW channel state (map entries / cell value
        under the reserved key / counter accumulator)."""
        return self.lww.snapshot((doc_id, store, channel))

    def document_seq(self, doc_id: str) -> int:
        dl = self.docs.get(doc_id)
        if dl is None:
            return 0
        return int(np.asarray(self.tstate.next_seq)[dl.lane]) - 1

    def close(self) -> None:
        # Graceful close persists progress; pending (unflushed) messages are
        # NOT emitted here — a crash-restart replays them from the last
        # committed offset, the same at-least-once window as the scalar deli.
        self._checkpoint()


def _detail(msg: DocumentMessage):
    if msg.data is not None:
        return json.loads(msg.data)
    return msg.contents or {}
