"""The TPU partition sequencer: the device pipeline on the serving path.

This is the TPU-batched IPartitionLambdaFactory of the north star (reference
services-core/src/lambdas.ts:36-73 + deli/lambda.ts:142-224): one lambda
owns a whole partition's documents, drains their boxcars into [B, T] message
tensors, and sequences them in ONE device program (ticket_kernel.
sequence_batched_strict — joins/leaves/system messages included). Admitted
merge-tree ops are then applied to device-resident per-channel segment
tables (mergetree.kernel) so the server materializes document state for
batched summarization, exactly the role Scribe's protocol replica plays in
the reference (scribe/lambda.ts:40) but vectorized across every document.

Host responsibilities are the irreducibly host-shaped ones: JSON parsing,
client-id interning, emission to the downstream topics (scriptorium/
broadcaster/scribe consume SequencedDocumentMessages unchanged), nacks,
and checkpointing.

Capacity discipline (SURVEY.md §7 hard parts 1/3): merge lanes live in
capacity buckets (one compiled program per bucket size). A lane that
overflows its bucket during apply is first zamboni-compacted and re-run;
if it still overflows it promotes to the next bucket — correct-by-recovery,
never correct-by-luck. The ticket client table grows the same way (K
doubles pre-flush when a window's join count could exceed it).
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree import kernel
from ..mergetree.catchup import (
    Unmodelable,
    looks_like_merge_op,
    wire_to_host_ops,
)
from ..mergetree.constants import DEFAULT_T_BUCKETS, PAGE_ROWS
from ..mergetree.host import OpBuilder, PayloadTable, extract_text
from ..mergetree.oppack import HostOp, OpKind, PackedOps, pack_ops
from ..mergetree.paging import PagedMergeStore, pages_for, pow2_pages
from ..mergetree.state import DocState, make_state
from ..protocol.messages import (
    Boxcar,
    DocumentMessage,
    ITrace,
    MessageType,
    Nack,
    NackContent,
    NACK_BAD_REF_SEQ,
    SequencedDocumentMessage,
)
from ..telemetry import device_stats, tracing
from ..telemetry.compile_ledger import ledger as compile_ledger
from ..telemetry.counters import (JitRetraceProbe, gauge, get as counter_get,
                                  increment, latency_window, nearest_rank,
                                  record_swallow)
from . import ticket_kernel as tk
from .lambdas.base import IPartitionLambda, LambdaContext
from .log import QueuedMessage



def _bucket(n: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"window of {n} exceeds max bucket {buckets[-1]}")


# ---------------------------------------------------------------------------
# merge lanes: device-resident per-channel segment tables, capacity-bucketed
# ---------------------------------------------------------------------------

class _MergeBucket:
    """A batch of merge lanes sharing one segment capacity (one compiled
    apply program per (capacity, T-bucket) pair)."""

    def __init__(self, capacity: int, lanes: int):
        self.capacity = capacity
        self.lanes = lanes
        self.state: DocState = make_state(capacity, batch=lanes)
        self.used: List[Optional[tuple]] = [None] * lanes  # lane key or None
        self._blank_row: Optional[DocState] = None  # built lazily, reused
        self._free: List[int] = []  # explicitly freed lanes (zeroed)
        self._next = 0              # frontier: lanes >= _next never used
        self.placer = None          # optional dp-mesh placement callable
        # Host-side UPPER BOUND of each lane's live row count: the donation
        # gate (tpu_sequencer._assess_windows) proves a window cannot
        # overflow from these hints alone — no device sync on the hot path.
        # count_hint is the CONFIRMED base (refreshed exactly from every
        # drained window's occupancy plane, from recovery put_rows, and at
        # compact ticks); hint_pending is the in-flight windows' staged-op
        # bound (added at dispatch, removed at that window's drain). The
        # live bound is their sum.
        self.count_hint = np.zeros(lanes, np.int64)
        self.hint_pending = np.zeros(lanes, np.int64)

    def grow(self) -> None:
        old = self.lanes
        grown = make_state(self.capacity, batch=old * 2)
        self.state = jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s) if g.ndim else s,
            grown, self.state)
        self.used.extend([None] * old)
        self.lanes = old * 2
        self.count_hint = np.concatenate(
            [self.count_hint, np.zeros(old, np.int64)])
        self.hint_pending = np.concatenate(
            [self.hint_pending, np.zeros(old, np.int64)])
        if self.placer is not None:
            self.state = self.placer(self.state)

    def alloc(self, key: tuple) -> int:
        # Free-list + frontier: O(1) per alloc (a linear first-None scan
        # is O(lanes^2) across a flush that admits thousands of channels).
        if self._free:
            i = self._free.pop()
        else:
            if self._next >= self.lanes:
                self.grow()
            i = self._next
            self._next += 1
        self.used[i] = key
        self.count_hint[i] = 0  # freed/frontier lanes are blank rows
        return i

    def free(self, lane: int) -> None:
        self.free_many([lane])

    def free_many(self, lanes: List[int]) -> None:
        # Zero the rows too: alloc() hands freed lanes to NEW channels, and
        # a dirty lane's stale segments would leak into the next channel's
        # materialization (summaries, catch-up seeds, LWW empty-base seed).
        # Batched: a recovery burst frees thousands of lanes and per-lane
        # scatters cost one device dispatch each.
        if not lanes:
            return
        for i in lanes:
            self.used[i] = None
        self._free.extend(lanes)
        self.count_hint[np.asarray(lanes, np.int64)] = 0
        self.hint_pending[np.asarray(lanes, np.int64)] = 0
        if self._blank_row is None:
            self._blank_row = make_state(
                self.capacity, anno_slots=self.state.anno_slots,
                overlap_slots=self.state.rem_clients.shape[-1])
        idx = jnp.asarray(np.asarray(lanes, np.int32))
        k = len(lanes)
        self.state = jax.tree_util.tree_map(
            lambda col, blank: col.at[idx].set(
                jnp.broadcast_to(blank, (k,) + blank.shape)),
            self.state, self._blank_row)

    def alloc_many(self, keys: List[tuple]) -> List[int]:
        return [self.alloc(key) for key in keys]

    def row(self, lane: int) -> DocState:
        """Extract one lane as a single-doc DocState (host-side gather)."""
        return jax.tree_util.tree_map(lambda x: x[lane], self.state)

    def put_row(self, lane: int, row: DocState,
                count_hint: Optional[int] = None) -> None:
        self.state = jax.tree_util.tree_map(
            lambda b, r: b.at[lane].set(r), self.state, row)
        self.count_hint[lane] = self.capacity if count_hint is None \
            else count_hint

    def put_rows(self, lanes: List[int], rows: DocState,
                 count_hints=None) -> None:
        """Scatter a [k, ...] sub-batch into k lanes in ONE pass.
        `count_hints` (aligned to `lanes`) keeps the donation gate's
        occupancy bound tight; omitted = pessimistic until the next
        compact-tick refresh."""
        idx = jnp.asarray(np.asarray(lanes, np.int32))
        self.state = jax.tree_util.tree_map(
            lambda col, r: col.at[idx].set(r), self.state, rows)
        self.count_hint[np.asarray(lanes, np.int64)] = \
            self.capacity if count_hints is None \
            else np.asarray(count_hints, np.int64)


def _stack_seed_rows(items: List[tuple], capacity: int, anno_slots: int,
                     overlap_slots: int) -> DocState:
    """[(key, seed_host_cols dict, min_seq, seq)] -> one [k, ...] DocState
    built entirely in host numpy, shipped as ONE transfer per column
    (the batched half of catchup.seed_host_cols)."""
    from ..mergetree.constants import DEV_NO_REMOVE, DEV_UNASSIGNED
    k = len(items)

    def full(val, *dims):
        return np.full((k, *dims), val, np.int32)

    out = {
        "length": full(0, capacity),
        "ins_seq": full(DEV_UNASSIGNED, capacity),
        "ins_client": full(-1, capacity),
        "local_seq": full(0, capacity),
        "rem_seq": full(DEV_NO_REMOVE, capacity),
        "rem_local_seq": full(0, capacity),
        "origin_op": full(-1, capacity),
        "origin_off": full(0, capacity),
    }
    rem_clients = full(-1, capacity, overlap_slots)
    anno = full(-1, capacity, anno_slots)
    count = np.zeros(k, np.int32)
    mins = np.zeros(k, np.int32)
    seqs = np.zeros(k, np.int32)
    for j, (_, cols, mseq, cseq) in enumerate(items):
        n = len(cols["length"])
        for name, arr in out.items():
            arr[j, :n] = cols[name]
        rem_clients[j, :n, 0] = cols["rem_client"]
        if "rem_overlap" in cols:
            ov = cols["rem_overlap"]
            w = min(ov.shape[1], overlap_slots - 1)
            rem_clients[j, :n, 1:1 + w] = ov[:, :w]
        if "anno" in cols:
            anno[j, :n] = cols["anno"]
        count[j], mins[j], seqs[j] = n, mseq, cseq
    return DocState(
        length=jnp.asarray(out["length"]),
        ins_seq=jnp.asarray(out["ins_seq"]),
        ins_client=jnp.asarray(out["ins_client"]),
        local_seq=jnp.asarray(out["local_seq"]),
        rem_seq=jnp.asarray(out["rem_seq"]),
        rem_local_seq=jnp.asarray(out["rem_local_seq"]),
        rem_clients=jnp.asarray(rem_clients),
        origin_op=jnp.asarray(out["origin_op"]),
        origin_off=jnp.asarray(out["origin_off"]),
        anno=jnp.asarray(anno),
        count=jnp.asarray(count),
        min_seq=jnp.asarray(mins),
        seq=jnp.asarray(seqs),
        overflow=jnp.zeros(k, jnp.bool_),
    )


def _repad_batch(rows: DocState, capacity: int) -> DocState:
    """Re-pad a [n, ...] sub-batch to a larger capacity (group promotion)."""
    n = rows.length.shape[0]
    base = make_state(capacity, anno_slots=rows.anno_slots,
                      overlap_slots=rows.rem_clients.shape[-1], batch=n)
    c = rows.capacity

    def widen(dst, src):
        if src.ndim <= 1:
            return src
        return dst.at[:, :c].set(src)

    return jax.tree_util.tree_map(widen, base, rows)


# Non-donating applies (kernel.apply_ops*_keep): the serving path keeps the
# pre-flush state alive until overflow recovery has cleared, so nothing is
# rebuilt on the recovery path (jax arrays are immutable; retaining the
# input is free). Wrapped in the retrace probe: serving windows bucket to
# a fixed (capacity, T) grid, so compile-cache growth after warmup means
# an unplanned signature leaked in — counted as kernel.retrace_count and
# exported via the monitor's /healthz (the runtime cross-check for
# fluidlint's static RETRACE_HAZARD rule).
_apply_keep_batched = JitRetraceProbe(kernel.apply_ops_batched_keep,
                                      name="kernel.merge_apply_batched")

# The paged apply (kernel.apply_ops_paged): gather-by-page-id -> the same
# batched op phases -> scatter-by-page-id, pool + page tables donated.
# Shapes bucket to (pow2 docs, pow2 pages, T-grid) so cache growth after
# warmup is a leaked signature, same contract as the bucketed probe.
_apply_paged_probe = JitRetraceProbe(kernel.apply_ops_paged,
                                     name="kernel.paged_apply")
# The non-donating twin for MESH-placed pools (serving_pipeline.md R6:
# donation never reaches a mesh-placed dispatch — warm-compile-cache
# reload corrupts donated sharded planes; MESH_DONATION_GATE lint-
# enforces the same contract). PagedMergeStore.donate picks the probe.
_apply_paged_keep_probe = JitRetraceProbe(kernel.apply_ops_paged_keep,
                                          name="kernel.paged_apply_keep")


class _PagedFlushGroup:
    """One paged fast flush's virtual merge bucket (R10): every channel
    whose page table rounds to the same pow2 page-count class this
    flush. The megakernel gathers each group as one [lanes, p2*rows]
    view, so `lanes` pow2-pads the member count — the plane width every
    window of the flush stages against. Rebuilt per flush by
    MergeLaneStore.begin_flush_groups; coordinates live only in the
    flush's job dicts (cross-flush placement stays the page table)."""

    __slots__ = ("p2", "keys", "lane_of")

    def __init__(self, p2: int):
        self.p2 = p2
        self.keys: List[tuple] = []
        self.lane_of: Dict[tuple, int] = {}

    def admit(self, key: tuple) -> int:
        lane = self.lane_of.get(key)
        if lane is None:
            lane = len(self.keys)
            self.lane_of[key] = lane
            self.keys.append(key)
        return lane

    @property
    def lanes(self) -> int:
        return pow2_pages(max(1, len(self.keys)))


class MergeLaneStore:
    """All merge lanes across capacity buckets + the shared payload table."""

    def __init__(self, capacities: Tuple[int, ...] = (64, 256, 1024),
                 lanes_per_bucket: int = 8,
                 t_buckets: Tuple[int, ...] = DEFAULT_T_BUCKETS,
                 paged: bool = False,
                 page_rows: Optional[int] = None,
                 mesh=None):
        self.capacities = tuple(capacities)
        self.t_buckets = tuple(t_buckets)
        # dp-mesh placement for the paged pool rides the partition-rule
        # table (mergetree/partition_rules.py); bucketed lane grids are
        # placed by the sequencer's bucket.placer instead.
        self.mesh = mesh
        # Paged lane memory (docs/paged_memory.md): segment rows live in
        # a refcounted page pool with per-doc page tables instead of the
        # capacity-bucket grid — growth is "append a page", so the whole
        # promote/fold/rescue ceremony (and its padding of every lane to
        # the storm doc's bucket) disappears from the apply path. The
        # bucket list stays empty in paged mode; every storage touchpoint
        # below branches on self.paged.
        self.paged = bool(paged)
        self.pages: Optional[PagedMergeStore] = None
        if self.paged:
            self.pages = PagedMergeStore(page_rows=page_rows or PAGE_ROWS,
                                         mesh=mesh)
        self.buckets = [] if self.paged else [
            _MergeBucket(c, lanes_per_bucket) for c in self.capacities]
        # Paged-mode telemetry: host rescues (the only fold/rescue-class
        # event left — annotate-ring/overlap-slot exhaustion) and
        # budgeted defrag passes. The paged smoke compares these against
        # the bucketed path's fold/rescue dispatch count.
        self.paged_rescues = 0
        self.page_compactions = 0
        self.fold_rescue_dispatches = 0  # device recovery dispatches
        # Per-flush page-group directory (R10 fast flush): virtual
        # buckets keyed by pow2 page-count class; see begin_flush_groups.
        self.flush_groups: List[_PagedFlushGroup] = []
        self._flush_group_of: Dict[int, int] = {}
        self.payloads = PayloadTable()
        self.builder = OpBuilder(self.payloads)
        self.where: Dict[tuple, Tuple[int, int]] = {}  # key -> (bucket, lane)
        self.opaque: set = set()  # lanes dropped (unparseable op seen)
        self.overflow_drops = 0  # lanes degraded after exhausting buckets
        self.flushes_since_compact = 0
        self.compact_every = 8
        self.folds = 0            # lanes host-folded (zamboni pack)
        self.fold_rows_reclaimed = 0
        # Overflow below this capacity promotes; at/above it folds.
        self.fold_min_capacity = min(
            (c for c in self.capacities if c >= 256),
            default=self.capacities[-1])
        # op_ids created by the lane's latest fold/rescue generation:
        # freed (PayloadTable free-list) when the next generation
        # supersedes them — otherwise a long-lived document retains
        # O(doc_size x folds) dead folded-run strings.
        self._fold_payloads: Dict[tuple, List[int]] = {}
        # Async-summary safety: summarize_documents_async workers resolve
        # through the SHARED payload table; while any are in flight,
        # frees defer (a recycled id would materialize the WRONG text
        # into the in-flight snapshot). Deferred ids drain on the next
        # main-thread free once the last guard releases.
        import threading
        self._extract_guards = 0
        self._deferred_frees: List[int] = []
        self._guard_lock = threading.Lock()
        # Fast-path arena blocks pin the WHOLE flush's raw wire buffers
        # (MergeArenaBlock.bufs) until every referencing lane moves off
        # them — without aging, a long-lived server retains its entire
        # raw ingest history in host memory. Blocks are tracked with
        # per-lane id lists; lanes release refs when a fold/rescue
        # reseeds their rows, and blocks older than block_age_ticks
        # compact ticks materialize their remaining (tiny) payloads so
        # the buffers can go.
        self._lane_blocks: Dict[tuple, set] = {}   # key -> blocks
        self._blocks: List[list] = []              # [ticks, block]
        self.block_age_ticks = 8                   # x compact_every flushes
        self.blocks_aged = 0
        # Demotion-fold memo: live-row count at the last fold attempt
        # that could not demote — retry only when the count changes (the
        # extract+coalesce probe costs ~ms/lane and a contended lane can
        # stay crowded-but-undemotable across many ticks).
        self._fold_skip: Dict[tuple, int] = {}
        # Tick-fold work cap: bounds the host fold per compact tick so
        # proactive folding smooths latency instead of creating its own
        # stop-the-world wave.
        self.fold_budget_per_tick = 64
        # Major payload-id collection cadence: every N compact ticks IF
        # the table grew past double its post-collection size (the
        # heap-doubling heuristic — dead slots cannot be counted via
        # free_ids alone, because slow-path ingest ids orphaned by a
        # fold are never individually freed).
        self.payload_compact_every = 64
        self.payload_compact_min_entries = 4096
        self._ticks_since_payload_compact = 0
        self._entries_after_last_compact = 0
        self.payload_compactions = 0
        # Renumbering while a chunked apply() still holds un-applied
        # HostOps (numbered against the old table) would corrupt the
        # stream's tail — the collection only runs between applies.
        self._in_apply = False
        # Monotone change generations per channel — incremental
        # summarization extracts (and transfers) only channels whose
        # generation advanced past a consumer's last-written snapshot
        # (per-ref, reference SummaryTracker/trackState server-side).
        self.change_gen: Dict[tuple, int] = {}
        self._gen_counter = 0
        # Summarize epoch (dirty-epoch extraction): the generation each
        # lane was last assembled at, plus the assembled chunk blobs
        # keyed by that generation. A clean lane (change_gen unchanged
        # since its cached assembly) skips device extraction, the D2H
        # transfer, AND host text/props assembly — the whole summarize
        # pass scales with the dirty count. Entries: key ->
        # (gen_at_dispatch, chunk_chars, snapshot dict). Callers must
        # treat returned snapshots as immutable (they are shared).
        # Memory: one assembled snapshot per live lane (~doc text size,
        # same order as the payload table's live text); dropped lanes
        # evict, dirty lanes overwrite — bounded by live state like the
        # arena-block aging bound, not by ingest history.
        self._snap_cache: Dict[tuple, tuple] = {}
        self.last_summarized_gen: Dict[tuple, int] = {}
        # Read-path catch-up safety (server/readpath.py): lanes seeded
        # from a summary whose entries still carried CONTENDED client
        # metadata mix two ordinal spaces on device (the summary's
        # quorum-join ordinals vs this store's interned ones), so the
        # catch-up artifact publisher cannot translate their client
        # fields back to wire ids unambiguously. Such lanes exclude
        # their document from the delta path (clients tail-replay, the
        # always-correct fallback).
        self.catchup_unsafe: set = set()

    # -- lane admission ----------------------------------------------------
    def lane_for(self, key: tuple) -> Tuple[int, int]:
        if key not in self.where:
            if self.paged:
                # Admission = one blank page + a page-table entry; the
                # placement tuple keeps the (bucket, lane) arity with a
                # fixed (-1, -1) sentinel — paged placement lives in
                # the page table, so there is no unique lane index here
                # and pretending otherwise (e.g. an insertion ordinal)
                # would collide after drops.
                self.pages.ensure(key)
                self.where[key] = (-1, -1)
            else:
                bucket = 0
                lane = self.buckets[bucket].alloc(key)
                self.where[key] = (bucket, lane)
        return self.where[key]

    # -- paged fast-flush group directory (R10) ----------------------------
    def begin_flush_groups(self) -> None:
        """Reset the per-flush page-group directory. Called at the top
        of every paged lane resolution (including mid-flush re-resolves
        after a rescue moved pages): staged windows always dispatch
        before any recovery runs, and in-flight ring entries snapshot
        their group info at dispatch, so rebuilding never orphans a
        live coordinate."""
        self.flush_groups: List[_PagedFlushGroup] = []
        self._flush_group_of: Dict[int, int] = {}

    def flush_lane_for(self, key: tuple, n_ops: int) -> Tuple[int, int]:
        """Paged fast-flush admission: returns the channel's (group,
        lane) coordinate for THIS flush. Pre-grows the doc's pages for
        the flush's worst case (2 rows per op + slack — the same bound
        the slow paged apply proves), so mid-kernel row overflow is
        structurally impossible and the doc's pow2 page class (its
        virtual bucket) is stable until the megakernel dispatches."""
        pg = self.pages
        self.lane_for(key)  # page-table + `where` sentinel admission
        pg.ensure_rows(key, pg.counts.get(key, 0) + 2 * n_ops + 8)
        p2 = pow2_pages(len(pg.tables[key]))
        g = self._flush_group_of.get(p2)
        if g is None:
            g = len(self.flush_groups)
            self._flush_group_of[p2] = g
            self.flush_groups.append(_PagedFlushGroup(p2))
        return g, self.flush_groups[g].admit(key)

    def mark_dirty(self, key: tuple) -> None:
        self._gen_counter += 1
        self.change_gen[key] = self._gen_counter

    def dirty_keys(self) -> set:
        """Channels whose change generation advanced past the summarize
        epoch (their last cached assembly) — what the next summarize
        pass will actually extract. Snapshots `where` first: monitor
        probes call this from the HTTP thread while the sequencing
        thread admits/drops lanes, and iterating the live dict would
        raise mid-mutation. The epoch read rides the summarize guard:
        an async assembly advances last_summarized_gen from its worker
        thread under the same lock."""
        with self._guard_lock:
            epoch = dict(self.last_summarized_gen)
        return {k for k in list(self.where)
                if self.change_gen.get(k, 0) > epoch.get(k, 0)}

    def cached_blob_count(self) -> int:
        """Assembled snapshots currently held by the summarize blob
        cache (the public, monitor-safe view of _snap_cache)."""
        with self._guard_lock:
            return len(self._snap_cache)

    def drop(self, key: tuple) -> None:
        """Mark a channel opaque: an op arrived the server cannot model
        (chunked/unknown payload); its device lane is abandoned."""
        if key in self.where:
            b, lane = self.where.pop(key)
            if self.paged:
                self.pages.free_all(key)
            else:
                self.buckets[b].free(lane)
        self._forget_lane_payloads(key)
        self.opaque.add(key)

    def _forget_lane_payloads(self, key: tuple) -> None:
        """The lane's rows are gone: free its fold generation and release
        every block ref. The blob-cache eviction rides the summarize
        guard, ordered STRICTLY AFTER the caller popped `where`: an
        async assembly's adoption (extract_assemble) checks `where` and
        writes the cache under the same lock, so either interleaving is
        safe — adopt-then-evict or evict-after-skip — and a dropped
        lane can never resurrect a cache entry."""
        self.free_payloads(self._fold_payloads.pop(key, ()))
        for block in self._lane_blocks.pop(key, ()):
            self._release_block_ref(block, key)
        self._fold_skip.pop(key, None)
        self.catchup_unsafe.discard(key)
        with self._guard_lock:
            self._snap_cache.pop(key, None)
            self.last_summarized_gen.pop(key, None)

    def _free_payload(self, op_id: int) -> None:
        self.free_payloads((op_id,))

    def free_payloads(self, ids) -> None:
        """Free via the guard (one lock round per BATCH): deferred while
        an async summary worker may still resolve the ids; drains the
        backlog when clear. Always called from the sequencing thread, so
        the drain never races PayloadTable._add."""
        with self._guard_lock:
            if self._extract_guards:
                self._deferred_frees.extend(ids)
                return
            backlog, self._deferred_frees = self._deferred_frees, []
        for i in backlog:
            self.payloads.free(i)
        for i in ids:
            self.payloads.free(i)

    def extract_guard_acquire(self) -> None:
        with self._guard_lock:
            self._extract_guards += 1

    def extract_guard_release(self) -> None:
        """Worker-thread safe: only decrements; the deferred backlog
        drains on the sequencing thread at the next free."""
        with self._guard_lock:
            self._extract_guards -= 1

    def _swap_fold_payloads(self, key: tuple, new_ids: set,
                            keep_ops=()) -> None:
        """Adopt a fold/rescue generation's payload ids for `key`, freeing
        the superseded generation (every row got a fresh id, so the old
        ones are unreferenced once the new rows are adopted). Block refs
        release too: after a reseed the lane's rows reference only the
        new generation — plus, for an overflow fold that re-ran the
        current window on device, that window's block ids (keep_ops)."""
        self.free_payloads([op_id
                            for op_id in self._fold_payloads.pop(key, ())
                            if op_id not in new_ids])
        self._fold_payloads[key] = sorted(new_ids)
        refs = self._lane_blocks.get(key)
        if refs:
            keep_ids = {op.op_id for op in keep_ops}
            kept = set()
            for block in list(refs):
                # Membership against the block's RECORDED ids for this
                # lane, not its id range: freed range ids recycle to
                # unrelated builder ops, and a range test would let such
                # an op spuriously pin an old block's buffers.
                if keep_ids and not keep_ids.isdisjoint(
                        block.lane_ids.get(key, ())):
                    kept.add(block)
                else:
                    self._release_block_ref(block, key)
            if kept:
                self._lane_blocks[key] = kept
            else:
                self._lane_blocks.pop(key, None)

    def note_block(self, block, lane_ids: Dict[tuple, list]) -> None:
        """Register a fast-flush arena block for aging. lane_ids maps each
        channel key to the block-global op ids admitted for it."""
        block.lane_ids = lane_ids
        self._blocks.append([0, block])
        for key in lane_ids:
            self._lane_blocks.setdefault(key, set()).add(block)

    def _release_block_ref(self, block, key: tuple) -> None:
        """A lane's rows no longer reference this block: free its ids (the
        slots recycle). Once the last lane departs, the registry entry
        drops at the next aging pass and the block — with the raw wire
        buffers it pins — becomes garbage."""
        self.free_payloads(block.lane_ids.pop(key, ()))

    def compact_payload_ids(self) -> bool:
        """Major collection (LWW compact_values' merge analog): renumber
        the LIVE payload ids and rebuild the table. The entries LIST
        grows one slot per ingested op (blocks append contiguously;
        holes recycle but the list never shrinks), so a long-lived
        server would hold an ever-growing slab of dead slots. Collects
        referenced ids from the origin_op + anno planes (run right
        after compact_batched, so rows past count are blanked),
        materializes block-backed payloads, renumbers the planes with a
        vectorized searchsorted remap, and drops every block. Skipped
        (retried next tick) while an async summary worker resolves the
        old ids. Returns True when it ran."""
        with self._guard_lock:
            if self._extract_guards:
                return False
            self._deferred_frees = []  # table is rebuilt wholesale
        per_bucket: List[Optional[tuple]] = []
        referenced: set = set()
        pool_planes: Optional[tuple] = None
        if self.paged:
            # One whole-pool pass: free pages are zeroed (-1 planes) and
            # padding rows stay blank, so the pool's unique ids ARE the
            # live reference set — no per-bucket walk.
            op_np = np.asarray(self.pages.pool.origin_op)
            an_np = np.asarray(self.pages.pool.anno)
            pool_planes = (op_np, an_np)
            referenced.update(int(v) for v in np.unique(op_np) if v >= 0)
            referenced.update(int(v) for v in np.unique(an_np) if v >= 0)
        for bucket in self.buckets:
            if not any(k is not None for k in bucket.used):
                per_bucket.append(None)
                continue
            op_np = np.asarray(bucket.state.origin_op)
            an_np = np.asarray(bucket.state.anno)
            per_bucket.append((op_np, an_np))
            referenced.update(int(v) for v in np.unique(op_np) if v >= 0)
            referenced.update(int(v) for v in np.unique(an_np) if v >= 0)
        order = sorted(referenced)
        sorted_old = np.asarray(order, np.int64)
        new_entries = [self.payloads.get(old) for old in order]

        def renumber(plane):
            live = plane >= 0
            idx = np.searchsorted(sorted_old, plane)
            return np.where(live, idx, -1).astype(np.int32)

        if pool_planes is not None:
            op_np, an_np = pool_planes
            self.pages.adopt_pool(self.pages.pool._replace(
                origin_op=jnp.asarray(renumber(op_np)),
                anno=jnp.asarray(renumber(an_np))))
        for bucket, host in zip(self.buckets, per_bucket):
            if host is None:
                continue
            op_np, an_np = host
            bucket.state = bucket.state._replace(
                origin_op=jnp.asarray(renumber(op_np)),
                anno=jnp.asarray(renumber(an_np)))
            if bucket.placer is not None:
                # jnp.asarray built host-resident replicated columns,
                # dropping the dp-mesh placement: re-place so major
                # collection preserves sharding (grow() does the same).
                bucket.state = bucket.placer(bucket.state)
        remap = {old: new for new, old in enumerate(order)}
        self._fold_payloads = {
            key: sorted(remap[i] for i in ids if i in remap)
            for key, ids in self._fold_payloads.items()}
        self.payloads.entries = new_entries
        self.payloads.free_ids = []
        self._blocks = []
        self._lane_blocks = {}
        self._entries_after_last_compact = len(new_entries)
        self.payload_compactions += 1
        return True

    def _age_blocks(self) -> None:
        from ..mergetree.host import _UNSET
        keep = []
        for rec in self._blocks:
            rec[0] += 1
            block = rec[1]
            if not block.lane_ids:
                continue  # every lane departed; drop the registry ref
            if rec[0] < self.block_age_ticks:
                # Drop fast_text's decoded-arena cache between ticks: it
                # serves the fold batches of ONE tick window; keeping it
                # for the block's whole aging life would double the
                # pinned arena memory.
                block._ascii_text = _UNSET
                keep.append(rec)
                continue
            # Old block still referenced (idle lanes never fold):
            # materialize the remaining payloads — a window's worth of
            # tiny strings per lane — so the flush's raw buffers free.
            # Materialized ids are superseded at the lane's next
            # fold/drop exactly like seed ids.
            for key in list(block.lane_ids):
                ids = block.lane_ids.pop(key)
                for op_id in ids:
                    self.payloads.entries[op_id] = block.resolve(op_id)
                self._fold_payloads.setdefault(key, []).extend(ids)
                refs = self._lane_blocks.get(key)
                if refs is not None:
                    refs.discard(block)
                    if not refs:
                        self._lane_blocks.pop(key, None)
            self.blocks_aged += 1
        self._blocks = keep

    @staticmethod
    def _seed_ids(cols: dict) -> set:
        ids = {int(i) for i in cols["origin_op"].tolist()}
        if "anno" in cols:
            ids.update(int(i) for i in np.unique(cols["anno"]) if i >= 0)
        return ids

    def seed(self, key: tuple, entries, min_seq: int,
             current_seq: int) -> bool:
        """Bootstrap a lane from snapshot segments (a document whose
        content shipped via the attach/client summary rather than ops —
        without this, the first op addressing snapshot content finds an
        empty lane and overflows every bucket). Picks the smallest bucket
        with 2x headroom; unmodelable or oversized snapshots degrade the
        channel to opaque."""
        from ..mergetree.catchup import Unmodelable, seed_host_cols
        from ..mergetree.state import state_from_numpy
        if key in self.where or key in self.opaque:
            return key in self.where
        if self.paged:
            return self._seed_paged(key, entries, min_seq, current_seq)
        allow_runs = matrix_base_key(key) is not None
        # Plain snapshot seed: no window to re-apply, so the widest
        # bucket may fill completely (last_slack=0) before degrading.
        b = self._seed_bucket_for(len(entries), last_slack=0)
        if b is None:
            self.opaque.add(key)
            return False
        bucket = self.buckets[b]
        try:
            cols = seed_host_cols(entries, self.payloads,
                                  anno_slots=bucket.state.anno_slots,
                                  allow_runs=allow_runs,
                                  allow_items=not allow_runs)
        except (Unmodelable, ValueError):
            self.opaque.add(key)
            return False
        row = state_from_numpy(
            cols, bucket.capacity,
            anno_slots=bucket.state.anno_slots)._replace(
            min_seq=jnp.asarray(min_seq, jnp.int32),
            seq=jnp.asarray(current_seq, jnp.int32))
        lane = bucket.alloc(key)
        bucket.put_row(lane, row, count_hint=len(cols["length"]))
        self.where[key] = (b, lane)
        self.mark_dirty(key)
        self._mark_catchup_safety(key, entries)
        # Track the seed generation like a fold's: the first fold (or a
        # drop) frees it instead of stranding the attach-time document
        # text in the shared table forever.
        self._swap_fold_payloads(key, self._seed_ids(cols))
        return True

    def _mark_catchup_safety(self, key: tuple, entries) -> None:
        """Seed-time gate for the read-path artifact publisher: summary
        entries still carrying contended client metadata seed quorum-join
        ordinals into a lane whose ops intern 0,1,2,… — the two spaces
        alias, so client-field translation back to wire ids is ambiguous
        for this lane (class docstring at catchup_unsafe)."""
        if any(e.get("client") is not None
               or e.get("removedClient") is not None
               or e.get("removedOverlapClients")
               for e in entries):
            self.catchup_unsafe.add(key)

    def _seed_paged(self, key: tuple, entries, min_seq: int,
                    current_seq: int) -> bool:
        """Paged snapshot seed: no bucket-fit degradation — any snapshot
        size fits, it just allocates more pages (the page pool grows by
        doubling like every other table). Only unmodelable payload
        shapes still degrade the channel to opaque."""
        from ..mergetree.catchup import Unmodelable, seed_host_cols
        from ..mergetree.state import state_from_numpy
        pg = self.pages
        allow_runs = matrix_base_key(key) is not None
        try:
            cols = seed_host_cols(entries, self.payloads,
                                  anno_slots=pg.anno_slots,
                                  allow_runs=allow_runs,
                                  allow_items=not allow_runs)
        except (Unmodelable, ValueError):
            self.opaque.add(key)
            return False
        n = len(cols["length"])
        capacity = pages_for(n, pg.page_rows) * pg.page_rows
        row = state_from_numpy(
            cols, capacity, anno_slots=pg.anno_slots)._replace(
            min_seq=jnp.asarray(min_seq, jnp.int32),
            seq=jnp.asarray(current_seq, jnp.int32))
        self.lane_for(key)
        pg.put_row(key, row, count=n)
        self.mark_dirty(key)
        self._mark_catchup_safety(key, entries)
        self._swap_fold_payloads(key, self._seed_ids(cols))
        return True

    # -- batched apply with overflow recovery ------------------------------
    def apply(self, streams: Dict[tuple, List[HostOp]]) -> None:
        """Apply per-lane op streams; windows longer than the largest
        T-bucket chunk into successive device passes (bulk catch-up)."""
        self._in_apply = True
        try:
            self._apply(streams)
        finally:
            self._in_apply = False

    def _apply(self, streams: Dict[tuple, List[HostOp]]) -> None:
        if self.paged:
            self._apply_paged(streams)
            with tracing.span("serving.gc", hist="serving.gc"):
                self.flushes_since_compact += 1
                if self.flushes_since_compact >= self.compact_every:
                    self.compact_all()
            return
        max_t = self.t_buckets[-1]
        while streams:
            window: Dict[tuple, List[HostOp]] = {}
            rest: Dict[tuple, List[HostOp]] = {}
            for key, ops in streams.items():
                if not ops:
                    continue
                window[key] = ops[:max_t]
                if len(ops) > max_t:
                    rest[key] = ops[max_t:]
            if not window:
                break
            self._apply_window(window)
            streams = rest

    def _apply_window(self, streams: Dict[tuple, List[HostOp]]) -> None:
        self._apply_streams(streams)
        with tracing.span("serving.gc", hist="serving.gc"):
            self.flushes_since_compact += 1
            if self.flushes_since_compact >= self.compact_every:
                self.compact_all()

    def _apply_streams(self, streams: Dict[tuple, List[HostOp]]) -> None:
        """One batched device pass per bucket; recover overflowing lanes by
        compact -> re-run -> promote. No GC tick: the in-ring fixup path
        (TpuSequencerLambda._finish_window) re-applies quarantined lanes
        through here while later windows are still in flight, and a
        compaction there would move lanes those windows already staged
        against."""
        per_bucket: Dict[int, Dict[int, List[HostOp]]] = {}
        for key, ops in streams.items():
            if key in self.opaque or not ops:
                continue
            b, lane = self.lane_for(key)
            self.mark_dirty(key)
            per_bucket.setdefault(b, {})[lane] = ops

        for b, lane_ops in sorted(per_bucket.items()):
            bucket = self.buckets[b]
            with tracing.span("serving.pack", hist="serving.pack",
                              stage="merge-oppack", bucket=b):
                t = _bucket(max(len(v) for v in lane_ops.values()),
                            self.t_buckets)
                streams_list = [lane_ops.get(i, [])
                                for i in range(bucket.lanes)]
                packed = pack_ops(streams_list, steps=t)
            pre = bucket.state
            with tracing.span("serving.dispatch", hist="serving.dispatch",
                              stage="merge-apply", bucket=b):
                new_state = _apply_keep_batched(pre, packed)
            with tracing.span("serving.readback", hist="serving.readback",
                              stage="merge-overflow", bucket=b):
                over = np.asarray(new_state.overflow)
            flagged = [i for i in range(bucket.lanes)
                       if over[i] and i in lane_ops]
            # Unconditional fold/rescue span: a clean window records the
            # stage at ~0 so flush captures always attribute it.
            with tracing.span("serving.fold_rescue",
                              hist="serving.fold_rescue", bucket=b):
                if flagged:
                    # Adopt the clean lanes; roll flagged lanes back to
                    # their pre-flush rows (one batched scatter), then
                    # recover them.
                    idx = jnp.asarray(np.asarray(flagged, np.int32))
                    new_state = jax.tree_util.tree_map(
                        lambda bcol, p: bcol.at[idx].set(p[idx]),
                        new_state, pre)
                bucket.state = new_state
                # Occupancy hints: each applied op adds at most 2 rows
                # (insert + split); recovery's put_rows re-hints flagged
                # lanes below.
                for i, ops in lane_ops.items():
                    bucket.count_hint[i] += 2 * len(ops)
                if flagged:
                    # One BATCHED compact->rerun->promote per level —
                    # per-lane device round-trips over a thin host link
                    # turn a 1k-lane overflow burst into minutes. Lane
                    # counts pad to powers of two so the compiled shapes
                    # stay bounded.
                    self._recover_batch(b, {i: lane_ops[i]
                                            for i in flagged})

    # -- the paged apply path (docs/paged_memory.md) -----------------------
    def _apply_paged(self, streams: Dict[tuple, List[HostOp]]) -> None:
        """Apply per-lane op streams against the page pool. Growth is
        pre-proven: each op adds at most 2 rows, so `ensure_rows(count +
        2*ops)` appends exactly the pages the worst case needs BEFORE
        the dispatch — a page-table write, no data movement — and row
        overflow is structurally impossible. Documents group by their
        pow2 page-count bucket, so the gathered view pads to the
        GROUP's depth, not the fleet-wide storm doc's, and a stream
        longer than the T grid rides ONE scanned program
        (serve_step.serve_paged_burst) instead of per-window passes.
        The only fold/rescue-class event left is annotate-ring/overlap-
        slot exhaustion (per-row, unfixable by capacity), handled by
        rollback-from-pre-view + the host rescue."""
        pg = self.pages
        groups: Dict[int, List[Tuple[tuple, List[HostOp]]]] = {}
        for key, ops in streams.items():
            if key in self.opaque or not ops:
                continue
            self.lane_for(key)
            self.mark_dirty(key)
            pg.ensure_rows(key, pg.counts.get(key, 0) + 2 * len(ops))
            p2 = pow2_pages(len(pg.tables[key]))
            groups.setdefault(p2, []).append((key, ops))
        for p2, items in sorted(groups.items()):
            self._apply_group_paged(p2, items)

    def _stage_paged_group(self, keys: List[tuple]):
        """Pow2-padded staging planes for one page-bucket group:
        (n_pad, pids [n_pad, p2], counts, mins, seqs — each [n_pad]).
        Padding rows carry page id -1 (gathers the reserved blank page,
        scatters out of bounds → dropped) and zeroed scalars: the ONE
        padding convention every paged dispatch site shares (apply,
        defrag tick, extract)."""
        pg = self.pages
        p2 = pow2_pages(max(len(pg.tables[k]) for k in keys))
        n = len(keys)
        n_pad = pow2_pages(n)  # next pow2: same bound as the page axis
        pids = np.full((n_pad, p2), -1, np.int32)
        pids[:n] = pg.page_ids_array(keys, p2)
        counts = np.zeros(n_pad, np.int32)
        mins = np.zeros(n_pad, np.int32)
        seqs = np.zeros(n_pad, np.int32)
        counts[:n], mins[:n], seqs[:n] = pg.scalars_arrays(keys)
        return n_pad, pids, counts, mins, seqs

    def _apply_group_paged(self, p2: int,
                           items: List[Tuple[tuple, List[HostOp]]]) -> None:
        pg = self.pages
        max_t = self.t_buckets[-1]
        keys = [k for k, _ in items]
        longest = max(len(ops) for _, ops in items)
        t = _bucket(min(longest, max_t), self.t_buckets)
        k_chunks = -(-longest // t)
        n = len(keys)
        n_pad, pids, counts, mins, seqs = self._stage_paged_group(keys)
        with tracing.span("serving.pack", hist="serving.pack",
                          stage="paged-oppack", pages=p2):
            pad_streams: List[List[HostOp]] = [ops for _, ops in items]
            pad_streams += [[] for _ in range(n_pad - n)]
            if k_chunks == 1:
                staged = pack_ops(pad_streams, steps=t)
            else:
                # Chunk the streams on the T grid and stack the chunks
                # into [K, B, T] planes: the scanned burst's xs. K pads
                # to a power of two with all-NOOP chunks (an exact
                # identity), bounding the compiled scan lengths.
                k_pad = pow2_pages(k_chunks)
                chunks = [pack_ops([s[c * t:(c + 1) * t]
                                    for s in pad_streams], steps=t)
                          for c in range(k_chunks)]
                chunks += [pack_ops([[] for _ in pad_streams], steps=t)
                           for _ in range(k_pad - k_chunks)]
                staged = PackedOps(*[
                    jnp.stack([getattr(c, f) for c in chunks])
                    for f in PackedOps._fields])
        stats_on = device_stats.enabled()
        with tracing.span("serving.dispatch", hist="serving.dispatch",
                          stage="paged-apply", pages=p2):
            args = (pg.pool, jnp.asarray(pids), jnp.asarray(counts),
                    jnp.asarray(mins), jnp.asarray(seqs), staged)
            st_dev = None
            if k_chunks == 1:
                probe = _apply_paged_probe if pg.donate \
                    else _apply_paged_keep_probe
                res = probe(*args, stats=stats_on)
                (pool2, _pids2, c2, m2, s2, over, pre) = res[:7]
                if stats_on:
                    st_dev = res[7]
            else:
                from . import serve_step
                burst = serve_step.serve_paged_burst if pg.donate \
                    else serve_step.serve_paged_burst_keep
                with compile_ledger.track("serve.paged_burst", burst):
                    res = burst(*args, stats_on)
                (pool2, _pids2, c2, m2, s2, over, _over_k, pre) = res[:8]
                if stats_on:
                    st_dev = res[8]
            pg.adopt_pool(pool2)
        with tracing.span("serving.readback", hist="serving.readback",
                          stage="paged-overflow", pages=p2):
            over_np = np.asarray(over)[:n]
            c2n = np.asarray(c2)[:n]
            m2n = np.asarray(m2)[:n]
            s2n = np.asarray(s2)[:n]
            if st_dev is not None:
                # The device telemetry plane rides the same join; a
                # K-chunk burst stacks per-chunk vectors — op kinds sum
                # across chunks, overflow/rows-live are final-state
                # facts (sticky carry flags), so the last chunk's values
                # are the group's.
                st_np = np.asarray(st_dev)
                if st_np.ndim == 2:
                    st_np = np.concatenate(
                        [st_np[:, :6].sum(0), st_np[-1, 6:]])
                host_vec = np.zeros(device_stats.N_PAGED, np.int64)
                all_kinds = np.fromiter(
                    (op.kind for _, ops in items for op in ops),
                    np.int64)
                host_vec[:6] = np.bincount(all_kinds, minlength=7)[1:7]
                host_vec[6] = int(over_np.sum())
                host_vec[7] = int(st_np[7])  # fill: device-only fact
                device_stats.fold_paged(st_np, host_vec)
        with tracing.span("serving.fold_rescue",
                          hist="serving.fold_rescue", pages=p2):
            good = np.flatnonzero(~over_np)
            if good.size:
                pg.adopt_scalars([keys[j] for j in good],
                                 c2n[good], m2n[good], s2n[good])
                for j in good.tolist():
                    key = keys[j]
                    pg.ops_since_compact[key] = \
                        pg.ops_since_compact.get(key, 0) \
                        + len(items[j][1])
                # One batched zeroing scatter for the whole group: the
                # 2-rows-per-op pre-grow means most multi-page docs free
                # something every window.
                pg.release_trailing_many(keys[j] for j in good.tolist())
            flagged = np.flatnonzero(over_np).tolist()
            if flagged:
                self._recover_paged(keys, items, pids, pre, flagged)

    def _recover_paged(self, keys, items, pids: np.ndarray, pre: DocState,
                       flagged: List[int]) -> None:
        """Rare unpredicted overflow (annotate ring / overlap slots):
        roll the flagged docs' pages back from the retained pre-view
        (one pow2-padded scatter), then host-rescue each with THIS
        stream's ops — more pages cannot fix per-row ring exhaustion,
        the host fold resolving rings into props can."""
        pg = self.pages
        tm = jax.tree_util.tree_map
        self.fold_rescue_dispatches += 1
        k = len(flagged)
        # Span coverage (docs/observability.md): the paged rescue is the
        # one fold/rescue-class event left on the paged path — always
        # spanned + histogrammed so a rescue storm attributes to a stage
        # instead of hiding inside serving.fold_rescue's tail.
        with tracing.span("serving.paged_rescue",
                          hist="serving.paged_rescue", flagged=k) as _sp:
            k_pad = pow2_pages(k)
            sel = np.asarray(flagged + [flagged[0]] * (k_pad - k),
                             np.int64)
            sub_pids = pids[sel].copy()
            sub_pids[k:] = -1  # padding rows scatter OOB -> drop
            sub_pre = tm(lambda x: x[jnp.asarray(sel)]
                         if getattr(x, "ndim", 0) else x, pre)
            rollback = kernel.rollback_pages if pg.donate \
                else kernel.rollback_pages_keep
            pg.adopt_pool(rollback(pg.pool, jnp.asarray(sub_pids),
                                   sub_pre))
            dropped = 0
            for j in flagged:
                key = keys[j]
                row = tm(lambda x: x[j] if getattr(x, "ndim", 0) else x,
                         pre)
                self.paged_rescues += 1
                increment("serving.paged_rescues")
                if self._rescue_paged(key, row, items[j][1]):
                    continue
                self.where.pop(key, None)
                pg.free_all(key)
                self._forget_lane_payloads(key)
                self.opaque.add(key)
                self.overflow_drops += 1
                dropped += 1
            if dropped:
                _sp.set(dropped=dropped)

    def _rescue_paged(self, key: tuple, row: DocState, ops) -> bool:
        """_rescue_lane's contract, page-backed: fold the pre-window row
        on the host (annotate rings resolve into props, acked runs
        coalesce), re-apply this stream's ops host-side, reseed into
        exactly the pages the folded content needs."""
        from ..mergetree.catchup import (Unmodelable, apply_host_ops,
                                         coalesce_entries, extract_entries)
        pg = self.pages
        try:
            mseq = int(np.asarray(row.min_seq))
            cseq = int(np.asarray(row.seq))
            entries = coalesce_entries(
                extract_entries(row, self.payloads, mseq, fold=True))
            new_entries = coalesce_entries(
                apply_host_ops(entries, ops, self.payloads, mseq, cseq))
        except (Unmodelable, ValueError):
            return False
        from ..mergetree.catchup import seed_host_cols
        from ..mergetree.constants import DEV_UNASSIGNED, UNASSIGNED_SEQ
        from ..mergetree.state import state_from_numpy
        mseq2 = max([mseq] + [op.msn for op in ops])
        cseq2 = max([cseq] + [op.seq for op in ops
                              if op.seq not in (DEV_UNASSIGNED,
                                                UNASSIGNED_SEQ)])
        try:
            cols = seed_host_cols(new_entries, self.payloads,
                                  anno_slots=pg.anno_slots)
        except (Unmodelable, ValueError):
            return False
        n = len(cols["length"])
        capacity = pages_for(n, pg.page_rows) * pg.page_rows
        row2 = state_from_numpy(
            cols, capacity, anno_slots=pg.anno_slots)._replace(
            min_seq=jnp.asarray(mseq2, jnp.int32),
            seq=jnp.asarray(cseq2, jnp.int32))
        pg.put_row(key, row2, count=n)
        self.mark_dirty(key)
        self.fold_rescue_dispatches += 1  # the per-lane put_row dispatch
        self._swap_fold_payloads(key, self._seed_ids(cols))
        pg.ops_since_compact.pop(key, None)
        return True

    def _compact_tick_paged(self) -> None:
        """Page-granular zamboni tick: fully-dead trailing pages already
        released at every apply, so this pass only defrags FRAGMENTED
        documents — ranked by applied-op volume since their last pass
        (the host-visible upper bound on new tombstones) — under the
        same per-tick budget the bucketed fold uses, releasing whatever
        pages the left-pack empties."""
        pg = self.pages
        cands = [key for key, v in pg.ops_since_compact.items()
                 if v > 0 and key in pg.tables]
        if not cands:
            return
        cands.sort(key=lambda k: -pg.ops_since_compact[k])
        cands = cands[:self.fold_budget_per_tick]
        groups: Dict[int, List[tuple]] = {}
        for key in cands:
            groups.setdefault(
                pow2_pages(len(pg.tables[key])), []).append(key)
        for _p2, keys in sorted(groups.items()):
            n = len(keys)
            _n_pad, pids, counts, mins, seqs = \
                self._stage_paged_group(keys)
            compact = kernel.compact_pages if pg.donate \
                else kernel.compact_pages_keep
            pool2, _, c2 = compact(
                pg.pool, jnp.asarray(pids), jnp.asarray(counts),
                jnp.asarray(mins), jnp.asarray(seqs))
            pg.adopt_pool(pool2)
            c2n = np.asarray(c2)[:n]
            # Zamboni reclamation from the host count mirrors (the pre
            # counts) vs the compacted counts — gated with the rest of
            # the device-stats surface so the counter means the same
            # thing whatever path fed it (extract-path reclaim lands in
            # device.extract.rows_reclaimed; this defrag-tick counter is
            # disjoint from it).
            if device_stats.enabled():
                increment("zamboni.rows_reclaimed",
                          int((counts[:n].astype(np.int64)
                               - c2n.astype(np.int64)).sum()))
            pg.adopt_scalars(keys, c2n, mins[:n], seqs[:n])
            pg.release_trailing_many(keys)
            for key in keys:
                pg.ops_since_compact.pop(key, None)
            self.page_compactions += n

    def paged_stats(self) -> dict:
        """The paged block's bench/monitor surface."""
        pg = self.pages
        return {
            "pages_in_use": pg.pages_in_use,
            "pool_pages": pg.allocator.capacity,
            "page_fill_frac": round(pg.page_fill_frac(), 4),
            "page_rows": pg.page_rows,
            "paged_rescues": self.paged_rescues,
            "page_compactions": self.page_compactions,
            "pool_grows": pg.pool_grows,
        }

    @staticmethod
    def _pad_pow2(sub: DocState, packed: PackedOps, n: int,
                  capacity: int):
        """Pad a recovery sub-batch to a power-of-two lane count with
        empty rows + NOOP streams: the compiled (lanes, capacity, t)
        shapes stay bounded at log2 variants instead of one per distinct
        overflow-burst size."""
        tm = jax.tree_util.tree_map
        n_pad = 1 << max(n - 1, 0).bit_length()
        if n_pad == n:
            return sub, packed
        base = make_state(capacity, anno_slots=sub.anno_slots,
                          overlap_slots=sub.rem_clients.shape[-1],
                          batch=n_pad)
        sub = tm(lambda full, s: full.at[:n].set(s)
                 if getattr(full, "ndim", 0) else s, base, sub)
        packed = tm(lambda x: jnp.concatenate(
            [x, jnp.zeros((n_pad - n,) + x.shape[1:], x.dtype)], 0), packed)
        return sub, packed

    def _recover_batch(self, b: int,
                       lane_ops: Dict[int, List[HostOp]]) -> None:
        """Batched overflow recovery (the only recovery path — one lane is
        a batch of one): stack the flagged lanes' pre-flush rows into a
        sub-batch, compact + re-run them together, then group-promote the
        still-overflowing remainder upward; opaque at exhaustion."""
        tm = jax.tree_util.tree_map
        lanes = sorted(lane_ops)
        n = len(lanes)
        bucket = self.buckets[b]
        take = np.asarray(lanes)
        sub = tm(lambda x: x[take] if getattr(x, "ndim", 0) else x,
                 bucket.state)
        t = _bucket(max(len(v) for v in lane_ops.values()), self.t_buckets)
        packed = pack_ops([lane_ops[i] for i in lanes], steps=t)
        sub, packed = self._pad_pow2(sub, packed, n, bucket.capacity)
        # Attempt 1: compact in place and re-run at this capacity.
        self.fold_rescue_dispatches += 1
        compacted = kernel.compact_batched(sub)
        redone = _apply_keep_batched(compacted, packed)
        over = np.asarray(redone.overflow)
        ok_j = [j for j in range(len(lanes)) if not over[j]]
        bad_j = [j for j in range(len(lanes)) if over[j]]
        if ok_j:
            sel = np.asarray(ok_j)
            # Exact counts ride the same sync the overflow read already
            # paid: recovered lanes re-qualify for deferral/donation
            # immediately instead of staying pessimistic until the next
            # compact tick.
            cnts = np.asarray(redone.count)
            bucket.put_rows([lanes[j] for j in ok_j],
                            tm(lambda x: x[sel], redone),
                            count_hints=cnts[sel])
        # Attempt 2: host-fold acked runs and re-run at the SAME
        # capacity. Sustained typing overflows with mostly-acked rows
        # (device compaction cannot merge them — payload bytes live
        # host-side), and promotion would climb to capacities whose
        # apply cost scales with C (measured steady-state ingest on the
        # CPU host: 139k -> 75k -> 17k ops/s at C=64/256/1024). The fold
        # caps that climb; only lanes whose live in-window rows genuinely
        # exceed the fold capacity still promote past it. Buckets BELOW
        # fold_min_capacity promote instead (warm shapes, one batched
        # pass): folding there would fire every ~(C - window)/window
        # flushes and the per-lane host fold cost would dominate — the
        # fold amortizes ~13x wider at 256 for keystroke windows.
        if bad_j and bucket.capacity >= self.fold_min_capacity:
            bad_j = self._fold_rerun_batch(bucket, lanes, bad_j,
                                           compacted, packed, lane_ops)
        carried = [bucket.used[lanes[j]] for j in bad_j]  # keys carrying up
        # Pre-apply row index + this window's ops per carried key: the
        # host-fold rescue (rare: only lanes that exhaust every capacity
        # promotion) slices `compacted` lazily — no per-lane gathers on
        # the batched path.
        rescue_src = {bucket.used[lanes[j]]: (j, lane_ops[lanes[j]])
                      for j in bad_j}
        keep = bad_j                 # their row indices into src/packed
        bucket.free_many([lanes[j] for j in bad_j])
        src = compacted
        for nb in range(b + 1, len(self.buckets)):
            if not carried:
                return
            n = len(keep)
            sel = np.asarray(keep)
            src = tm(lambda x: x[sel] if getattr(x, "ndim", 0) else x, src)
            packed = tm(lambda x: x[sel], packed)
            target = self.buckets[nb]
            wide = _repad_batch(src, target.capacity)
            wide, packed = self._pad_pow2(wide, packed, n, target.capacity)
            self.fold_rescue_dispatches += 1
            redone = _apply_keep_batched(wide, packed)
            over = np.asarray(redone.overflow)
            ok_k = [k for k in range(len(carried)) if not over[k]]
            if ok_k:
                new_lanes = target.alloc_many([carried[k] for k in ok_k])
                sel_ok = np.asarray(ok_k)
                target.put_rows(new_lanes, tm(lambda x: x[sel_ok], redone),
                                count_hints=np.asarray(
                                    redone.count)[sel_ok])
                for k, nl in zip(ok_k, new_lanes):
                    self.where[carried[k]] = (nb, nl)
            keep = [k for k in range(len(carried)) if over[k]]
            carried = [carried[k] for k in keep]
            src = wide
        for key in carried:
            j, ops = rescue_src[key]
            row = tm(lambda x: x[j] if getattr(x, "ndim", 0) else x,
                     compacted)
            if self._rescue_lane(key, row, ops):
                continue
            self.where.pop(key, None)
            self._forget_lane_payloads(key)
            self.opaque.add(key)
            self.overflow_drops += 1

    def _fold_rerun_batch(self, bucket, lanes: List[int], bad_j: List[int],
                          compacted: DocState, packed,
                          lane_ops: Dict[int, List[HostOp]]) -> List[int]:
        """Overflow attempt 2: fold the flagged lanes' acked runs on the
        host (coalesce_entries — the zamboni pack step the device cannot
        do) and re-run this window at the SAME capacity, batched. Returns
        the lane indices that still overflow (those carry into the
        promotion cascade). One D2H slice in, one batched apply + one
        put_rows out."""
        from ..mergetree.catchup import (Unmodelable, coalesce_entries,
                                         extract_entries, seed_host_cols)
        tm = jax.tree_util.tree_map
        sel = np.asarray(bad_j)
        host_rows = jax.device_get(tm(
            lambda x: x[sel] if getattr(x, "ndim", 0) else x, compacted))
        folded: List[tuple] = []  # (j, key, cols, mseq, cseq)
        for k, j in enumerate(bad_j):
            key = bucket.used[lanes[j]]
            row = tm(lambda x: x[k] if getattr(x, "ndim", 0) else x,
                     host_rows)
            mseq = int(row.min_seq)
            cseq = int(row.seq)
            allow_runs = matrix_base_key(key) is not None
            try:
                entries = coalesce_entries(
                    extract_entries(row, self.payloads, mseq, fold=True))
                # Re-run headroom: each window op costs at most 2 rows
                # (insert + split). Not enough -> promotion is correct.
                need = len(entries) + 2 * len(lane_ops[lanes[j]]) + 8
                if need > bucket.capacity:
                    continue
                cols = seed_host_cols(
                    entries, self.payloads,
                    anno_slots=int(row.anno.shape[-1]),
                    allow_runs=allow_runs, allow_items=not allow_runs)
            except (Unmodelable, ValueError):
                continue  # ring depth, odd payloads: promotion handles it
            folded.append((j, key, cols, mseq, cseq))
        if not folded:
            return bad_j
        rows = _stack_seed_rows(
            [(key, cols, ms, cs) for _, key, cols, ms, cs in folded],
            bucket.capacity, bucket.state.anno_slots,
            bucket.state.rem_clients.shape[-1])
        psel = np.asarray([j for j, *_ in folded])
        sub_packed = tm(lambda x: x[psel], packed)
        rows, sub_packed = self._pad_pow2(rows, sub_packed, len(folded),
                                          bucket.capacity)
        self.fold_rescue_dispatches += 1
        redone = _apply_keep_batched(rows, sub_packed)
        over = np.asarray(redone.overflow)
        adopted = [k for k in range(len(folded)) if not over[k]]
        if adopted:
            idx = np.asarray(adopted)
            bucket.put_rows([lanes[folded[k][0]] for k in adopted],
                            tm(lambda x: x[idx], redone),
                            count_hints=[
                                len(folded[k][2]["length"])
                                + 2 * len(lane_ops[lanes[folded[k][0]]])
                                for k in adopted])
            self.folds += len(adopted)
            for k in adopted:
                # The fold reseeded the rows (coalesced segmentation, new
                # payload ids): any cached summary blob is stale even
                # though the window's mark_dirty already fired — keep the
                # epoch honest for callers that summarize mid-recovery.
                self.mark_dirty(folded[k][1])
        counts = np.asarray(host_rows.count)
        bad_pos = {j: k for k, j in enumerate(bad_j)}
        for k, (j, key, cols, _, _) in enumerate(folded):
            if over[k]:
                # Rerun still overflowed: this generation's fresh seed
                # payloads were never adopted — free them now.
                self.free_payloads(self._seed_ids(cols))
            else:
                self._swap_fold_payloads(key, self._seed_ids(cols),
                                         keep_ops=lane_ops[lanes[j]])
                self.fold_rows_reclaimed += (
                    int(counts[bad_pos[j]]) - len(cols["length"]))
        done = {folded[k][0] for k in adopted}
        return [j for j in bad_j if j not in done]

    def _rescue_lane(self, key: tuple, row: DocState, ops) -> bool:
        """Last resort before opaque: fold the lane on the HOST — annotate
        rings resolve into props, acked runs coalesce — re-apply this
        window's ops with the chunked escalating applier, and reseed into
        the smallest fitting bucket. Capacity promotion alone cannot fix
        ring-ACCUMULATION overflow (ring depth is fixed per bucket); the
        fold empties every ring, so only >anno_slots annotates on one
        segment within a single window can still defeat it."""
        from ..mergetree.catchup import (Unmodelable, apply_host_ops,
                                         coalesce_entries, extract_entries)
        try:
            mseq = int(np.asarray(row.min_seq))
            cseq = int(np.asarray(row.seq))
            entries = coalesce_entries(
                extract_entries(row, self.payloads, mseq, fold=True))
            new_entries = coalesce_entries(
                apply_host_ops(entries, ops, self.payloads, mseq, cseq))
        except (Unmodelable, ValueError):
            return False
        from ..mergetree.constants import DEV_UNASSIGNED, UNASSIGNED_SEQ
        mseq2 = max([mseq] + [op.msn for op in ops])
        cseq2 = max([cseq] + [op.seq for op in ops
                              if op.seq not in (DEV_UNASSIGNED,
                                                UNASSIGNED_SEQ)])
        # _seed_bucket_for: smallest with 2x headroom (a +8 fit would
        # re-overflow on the very next busy window and thrash the whole
        # recovery cascade per flush); the widest bucket accepts an
        # n + 8 fit as the final fallback.
        nb = self._seed_bucket_for(len(new_entries))
        if nb is None:
            return False
        bucket = self.buckets[nb]
        from ..mergetree.catchup import seed_host_cols
        from ..mergetree.state import state_from_numpy
        try:
            cols = seed_host_cols(new_entries, self.payloads,
                                  anno_slots=bucket.state.anno_slots)
        except (Unmodelable, ValueError):
            return False
        row2 = state_from_numpy(
            cols, bucket.capacity,
            anno_slots=bucket.state.anno_slots)._replace(
            min_seq=jnp.asarray(mseq2, jnp.int32),
            seq=jnp.asarray(cseq2, jnp.int32))
        lane = bucket.alloc(key)
        bucket.put_row(lane, row2, count_hint=len(new_entries))
        self.where[key] = (nb, lane)
        self.mark_dirty(key)
        self.fold_rescue_dispatches += 1
        self._swap_fold_payloads(key, self._seed_ids(cols))
        return True

    def compact_all(self) -> None:
        """Zamboni every bucket (reference mergeTree.ts:1422, run between
        batches so the gather cost amortizes, kernel.py design note),
        then pack crowded lanes host-side. Paged mode replaces both
        halves with the page-granular tick: no whole-fleet compaction
        pass, no host folds."""
        if self.paged:
            self._compact_tick_paged()
            self._age_blocks()
            self._ticks_since_payload_compact += 1
            self.maybe_compact_payload_ids()
            self.flushes_since_compact = 0
            return
        for bucket in self.buckets:
            if any(k is not None for k in bucket.used):
                bucket.state = kernel.compact_batched(bucket.state)
                # Exact occupancy refresh at the safe boundary: lanes that
                # went pessimistic (recovery put_rows) re-qualify for the
                # donating dispatch. One small D2H per bucket per tick.
                bucket.count_hint = np.asarray(
                    bucket.state.count).astype(np.int64).copy()
        self._fold_crowded()
        self._age_blocks()
        self._ticks_since_payload_compact += 1
        self.maybe_compact_payload_ids()
        self.flushes_since_compact = 0

    def maybe_compact_payload_ids(self) -> None:
        """Cadence + size gate for the major collection. Safe-boundary
        aware: skipped while a chunked apply() holds un-applied HostOps
        (their op_ids are numbered against the old table — renumbering
        mid-stream corrupts the tail), so pure slow-path servers fire it
        from the flush boundary instead (TpuSequencerLambda.flush)."""
        if self._ticks_since_payload_compact < self.payload_compact_every \
                or self._in_apply:
            return
        # Only worth the plane round-trip when the table doubled since
        # the last collection (or its initial floor).
        threshold = max(self.payload_compact_min_entries,
                        2 * self._entries_after_last_compact)
        if len(self.payloads.entries) >= threshold:
            if self.compact_payload_ids():
                self._ticks_since_payload_compact = 0
        else:
            self._ticks_since_payload_compact = 0

    # Fold when live rows pass 3/4 of capacity; the per-lane cadence is
    # therefore ~capacity/4 ops, so the host cost amortizes wider as
    # documents grow.
    FOLD_NUM, FOLD_DEN = 3, 4

    def _seed_bucket_for(self, n: int, last_slack: int = 8) -> \
            Optional[int]:
        """Smallest bucket with 2x headroom (a tight fit would
        re-overflow next window and thrash); the widest bucket accepts a
        fit with `last_slack` spare rows as the final fallback —
        rescue/fold need room to re-apply a window (slack 8), a plain
        snapshot seed does not (slack 0)."""
        last = len(self.buckets) - 1
        for nb, bucket in enumerate(self.buckets):
            if n * 2 <= bucket.capacity or \
                    (nb == last and n + last_slack <= bucket.capacity):
                return nb
        return None

    def _fold_crowded(self) -> None:
        """Host-side pack — the serving half of the reference's zamboni
        scour/pack (mergeTree.ts:1289): device compaction frees removed
        rows but cannot merge ACKED adjacent rows (payload bytes live
        host-side as origin slices), so sustained typing grows one row
        per op and climbs capacity buckets whose apply cost scales with
        capacity (measured steady-state ingest on the CPU host: 139k ->
        75k -> 17k ops/s at C=64/256/1024, with multi-second promotion
        stalls at each boundary). Folding acked runs through
        coalesce_entries and reseeding into the smallest fitting bucket
        keeps long-lived documents in the fast small buckets. Candidate
        rows leave the device in ONE slice per bucket and folded lanes
        return in ONE batched put per destination bucket (per-lane
        round-trips over a tunneled chip pay a ~30-70 ms RPC floor
        each)."""
        from ..mergetree.catchup import (Unmodelable, coalesce_entries,
                                         extract_entries, seed_host_cols)
        tm = jax.tree_util.tree_map
        dest: Dict[int, List[tuple]] = {}  # nb -> [(key, cols, mseq, cseq)]
        budget = self.fold_budget_per_tick
        for b, bucket in enumerate(self.buckets):
            if not any(k is not None for k in bucket.used):
                continue
            counts = np.asarray(bucket.state.count)
            mseqs = np.asarray(bucket.state.min_seq)
            # Near-overflow lanes in fold-eligible buckets fold ahead of
            # time (same-bucket reseed allowed, budget-capped): spreading
            # the host fold across ticks instead of letting a cohort of
            # lockstep lanes all hit the synchronized overflow fold in
            # one flush (a p99 latency cliff).
            near_ok = bucket.capacity >= self.fold_min_capacity
            if b == 0 and not near_ok:
                # Neither demotion (no smaller bucket) nor refold
                # (below fold_min_capacity) is possible here: probing
                # would burn budget + extract time on guaranteed no-ops,
                # starving the buckets the budget exists to smooth.
                continue
            # The memo keys on (count, min_seq): an msn advance can turn
            # a previously-undemotable lane foldable without its row
            # count changing.
            cands = [i for i, key in enumerate(bucket.used)
                     if key is not None
                     and int(counts[i]) * self.FOLD_DEN
                     >= bucket.capacity * self.FOLD_NUM
                     and self._fold_skip.get(key)
                     != (int(counts[i]), int(mseqs[i]))]
            if len(cands) > budget:
                cands = sorted(cands, key=lambda i: -int(counts[i]))
                cands = cands[:budget]
            budget -= len(cands)
            if not cands:
                continue
            take = jnp.asarray(np.asarray(cands, np.int32))
            # One DEVICE dispatch per candidate slice (the unit
            # fold_rescue_dispatches counts everywhere — the paged
            # smoke's ceremony-cut gate compares it across engines, so
            # per-key counting here would inflate the bucketed side).
            self.fold_rescue_dispatches += 1
            sub = jax.device_get(tm(
                lambda x: x[take] if getattr(x, "ndim", 0) else x,
                bucket.state))
            freed: List[int] = []
            for j, lane in enumerate(cands):
                key = bucket.used[lane]
                row = tm(lambda x: x[j] if getattr(x, "ndim", 0) else x,
                         sub)
                mseq = int(row.min_seq)
                cseq = int(row.seq)
                allow_runs = matrix_base_key(key) is not None
                try:
                    entries = coalesce_entries(
                        extract_entries(row, self.payloads, mseq,
                                        fold=True))
                    nb = self._seed_bucket_for(len(entries))
                    # Accept a demotion (content shrank: cheaper
                    # capacity) or, for a fold-eligible bucket, a
                    # near-overflow fold in place that actually reclaims
                    # rows (>= half) — same-bucket rebuilds that reclaim
                    # little would be pure churn; the overflow-time fold
                    # still owns contended lanes.
                    near = (near_ok
                            and int(counts[lane]) * 8
                            >= bucket.capacity * 7
                            and len(entries) * 2 <= int(counts[lane]))
                    demote = nb is not None and nb < b
                    refold = nb == b and near
                    if not (demote or refold):
                        self._fold_skip[key] = (int(counts[lane]),
                                                int(mseqs[lane]))
                        continue
                    cols = seed_host_cols(
                        entries, self.payloads,
                        anno_slots=int(row.anno.shape[-1]),
                        allow_runs=allow_runs,
                        allow_items=not allow_runs)
                except (Unmodelable, ValueError):
                    self._fold_skip[key] = (int(counts[lane]),
                                            int(mseqs[lane]))
                    continue  # leave the lane untouched; fold is optional
                dest.setdefault(nb, []).append((key, cols, mseq, cseq))
                freed.append(lane)
                self._fold_skip.pop(key, None)
                # Reseeded rows = new segmentation + payload ids: a
                # cached summary blob assembled before the fold no longer
                # describes the lane — advance the change generation so
                # dirty-epoch extraction re-assembles it.
                self.mark_dirty(key)
                self.folds += 1
                self.fold_rows_reclaimed += int(counts[lane]) \
                    - len(entries)
            if freed:
                bucket.free_many(freed)
        for nb, items in dest.items():
            target = self.buckets[nb]
            self.fold_rescue_dispatches += 1  # one batched put per dest
            lanes = target.alloc_many([key for key, *_ in items])
            target.put_rows(lanes, _stack_seed_rows(
                items, target.capacity, target.state.anno_slots,
                target.state.rem_clients.shape[-1]),
                count_hints=[len(cols["length"])
                             for _, cols, *_ in items])
            for (key, cols, *_), lane in zip(items, lanes):
                self.where[key] = (nb, lane)
                self._swap_fold_payloads(key, self._seed_ids(cols))

    # -- batched summary extraction ----------------------------------------
    def extract_dispatch(self, only: Optional[set] = None,
                         chunk_chars: int = 10000) -> tuple:
        """Phase 1 (device, async): launch ONE fused zamboni+extraction
        pass per bucket (kernel.compact_extract_batched — compaction and
        snapshot packing share a single keep-mask/prefix-sum/gather, and
        the bucket adopts the compacted state). The returned jobs hold
        in-flight device arrays — jax dispatch is asynchronous, so the
        caller can keep sequencing the next window while these execute
        (the reference's pipeline-stage overlap,
        kafka-service/README.md:58-60).

        Dirty-epoch extraction: lanes whose change generation still
        matches their cached assembly (the summarize epoch) skip device
        extraction entirely and return their previous blobs via the
        second element. Remaining dirty lanes gather into a pow2-padded
        sub-batch (kernel.gather_rows_pow2, bounded compile shapes), so
        extraction compute AND the D2H transfer scale with the dirty
        count, not the fleet size. `only` further restricts the keys
        considered. Returns (jobs, cached_snapshots)."""
        if self.paged:
            return self._extract_dispatch_paged(only, chunk_chars)
        jobs = []
        cached: Dict[tuple, dict] = {}
        # One lock round for the whole scan: the blob cache is written
        # from the async-summary worker under the guard, so the dispatch
        # reads a coherent epoch snapshot instead of the live dict.
        with self._guard_lock:
            snap_view = dict(self._snap_cache)
        for bucket in self.buckets:
            lanes = []
            live = 0
            for i, key in enumerate(bucket.used):
                if key is None:
                    continue
                live += 1
                if only is not None and key not in only:
                    continue
                hit = snap_view.get(key)
                if hit is not None and hit[0] == self.change_gen.get(key, 0) \
                        and hit[1] == chunk_chars:
                    cached[key] = hit[2]
                    continue
                lanes.append((i, key))
            if not lanes:
                continue
            # Generations captured AT DISPATCH: ops applied while an async
            # assembly is in flight advance change_gen past these, so the
            # cache entry written later correctly reads as stale.
            gens = {key: self.change_gen.get(key, 0) for _, key in lanes}
            # Device telemetry (static at dispatch): the fused zamboni+
            # extract also returns its PRE-compaction per-doc row counts
            # so the host can report zamboni reclamation without a
            # separate fetch of the device-resident pre state (the
            # counts ride the assemble join's existing transfers).
            stats_on = device_stats.enabled()
            if len(lanes) == live:
                # Every live lane extracts: fuse over the whole bucket
                # state and adopt the compacted result (the summarize
                # pass IS this tick's zamboni for these lanes).
                with compile_ledger.track("kernel.compact_extract",
                                          kernel.compact_extract_batched):
                    res = kernel.compact_extract_batched(
                        bucket.state, stats=stats_on)
                new_state, packed = res[0], res[1]
                pre_counts = res[2] if stats_on else None
                bucket.state = new_state
                jobs.append((packed, lanes, new_state.seq,
                             new_state.min_seq, gens, pre_counts))
            else:
                sub, _n = kernel.gather_rows_pow2(
                    bucket.state, [i for i, _ in lanes])
                with compile_ledger.track("kernel.compact_extract",
                                          kernel.compact_extract_batched):
                    res = kernel.compact_extract_batched(
                        sub, stats=stats_on)
                packed = res[1]
                pre_counts = res[2] if stats_on else None
                # Lane indices become sub-batch rows.
                jobs.append((packed,
                             [(j, key) for j, (_, key)
                              in enumerate(lanes)],
                             sub.seq, sub.min_seq, gens, pre_counts))
        if cached:
            increment("summarize.blob_cache.hits", len(cached))
        return jobs, cached

    def _extract_dispatch_paged(self, only: Optional[set],
                                chunk_chars: int) -> tuple:
        """Paged phase-1 extraction: dirty lanes group by their pow2
        page bucket and each group runs ONE fused zamboni+extract over
        gathered page views (kernel.compact_extract_paged, pool adopted
        in place). The packed rows keep the extract_visible_batched
        layout, so phase 2 (extract_assemble / assemble_snapshot) runs
        unchanged. Counts adopt synchronously — the host scalar mirrors
        are authoritative and the next apply's page pre-growth proof
        reads them — then trailing pages release: a summarize pass IS
        these lanes' zamboni, exactly like the bucketed fuse."""
        pg = self.pages
        jobs = []
        cached: Dict[tuple, dict] = {}
        lanes: List[tuple] = []
        with self._guard_lock:
            snap_view = dict(self._snap_cache)
        for key in list(self.where):
            if key not in pg.tables:
                continue
            if only is not None and key not in only:
                continue
            hit = snap_view.get(key)
            if hit is not None and hit[0] == self.change_gen.get(key, 0) \
                    and hit[1] == chunk_chars:
                cached[key] = hit[2]
                continue
            lanes.append(key)
        groups: Dict[int, List[tuple]] = {}
        for key in lanes:
            groups.setdefault(
                pow2_pages(len(pg.tables[key])), []).append(key)
        for _p2, keys in sorted(groups.items()):
            gens = {key: self.change_gen.get(key, 0) for key in keys}
            n = len(keys)
            _n_pad, pids, counts, mins, seqs = \
                self._stage_paged_group(keys)
            cextract = kernel.compact_extract_paged if pg.donate \
                else kernel.compact_extract_paged_keep
            with compile_ledger.track("kernel.compact_extract_paged",
                                      cextract):
                pool2, _, c2, packed = cextract(
                    pg.pool, jnp.asarray(pids), jnp.asarray(counts),
                    jnp.asarray(mins), jnp.asarray(seqs))
            pg.adopt_pool(pool2)
            c2n = np.asarray(c2)[:n]
            if device_stats.enabled():
                # Paged zamboni reclamation needs no device plane: the
                # host count mirrors ARE the pre counts. (Extract-path
                # reclaim lands ONLY in device.extract.rows_reclaimed;
                # zamboni.rows_reclaimed is the defrag tick's counter —
                # disjoint, so the flush span can sum the pair.)
                reclaimed = int((counts[:n].astype(np.int64)
                                 - c2n.astype(np.int64)).sum())
                device_stats.fold_extract(
                    [n, int(c2n.sum()), reclaimed])
            pg.adopt_scalars(keys, c2n, mins[:n], seqs[:n])
            pg.release_trailing_many(keys)
            for key in keys:
                pg.ops_since_compact.pop(key, None)
            jobs.append((packed, list(enumerate(keys)), seqs, mins, gens,
                         None))
        if cached:
            increment("summarize.blob_cache.hits", len(cached))
        return jobs, cached

    def extract_assemble(self, jobs: List[tuple],
                         chunk_chars: int = 10000,
                         cached: Optional[Dict[tuple, dict]] = None
                         ) -> Dict[tuple, dict]:
        """Phase 2 (host): D2H transfer + text/props assembly touching only
        the visible rows of the DIRTY lanes; clean lanes ride through from
        the blob cache. Returns {lane_key: {"header", "chunks"}} — chunked
        snapshot shape per reference SnapshotV1 (snapshotV1.ts:33-40).
        Newly assembled snapshots enter the blob cache at their dispatch
        generation, advancing the summarize epoch."""
        from ..mergetree.host import assemble_snapshot

        # The payload-table read from the async-summary worker thread
        # rides the extract-guard protocol, not a mutual-exclusion
        # lock: summarize_documents_async holds _extract_guards while
        # this runs, so the sequencing thread DEFERS every free
        # (free_payloads) instead of recycling an id the assembly is
        # resolving. fluidlint cannot see that protocol, so the access
        # is declared safe here and verified at runtime by
        # testing/lockcheck.py.
        # fluidlint: disable=SHARED_STATE_NO_LOCK — worker read
        # protected by the _extract_guards deferred-free protocol
        table = self.payloads
        out: Dict[tuple, dict] = dict(cached or {})
        for packed, lanes, seq_dev, min_seq_dev, gens, *tail in jobs:
            pre_counts = tail[0] if tail else None
            t0 = time.perf_counter()
            packed = kernel.fetch_extracted(packed)
            increment("summarize.extract_ms",
                           (time.perf_counter() - t0) * 1000.0)
            seqs = np.asarray(seq_dev)
            min_seqs = np.asarray(min_seq_dev)
            if pre_counts is not None:
                # Zamboni reclamation from the device telemetry plane
                # (pre-compaction counts) vs the fetched post counts —
                # restricted to the job's REAL lanes (pow2 padding rows
                # duplicate row 0 and must not multi-count its reclaim).
                pre_np = np.asarray(pre_counts).astype(np.int64)
                post_np = np.asarray(packed[-1]).astype(np.int64)
                rows = [lane for lane, _ in lanes]
                reclaimed = int((pre_np[rows] - post_np[rows]).sum())
                device_stats.fold_extract(
                    [len(lanes), int(post_np[rows].sum()), reclaimed])
            for lane, key in lanes:
                snap = assemble_snapshot(
                    packed, table, lane,
                    min_seq=int(min_seqs[lane]), seq=int(seqs[lane]),
                    chunk_chars=chunk_chars)
                out[key] = snap
                # Monotone adoption, under the summarize guard: an async
                # worker finishing LATE must not clobber a newer-
                # generation entry an interleaved synchronous summarize
                # already cached, nor resurrect a cache entry for a lane
                # drop() evicted mid-assembly (the snapshot would be
                # retained forever for a channel that no longer exists).
                # drop() pops `where` BEFORE its guarded eviction, so
                # with the adoption check-and-write atomic under the
                # same lock, either interleaving is safe.
                with self._guard_lock:
                    # fluidlint: disable=SHARED_STATE_NO_LOCK —
                    # GIL-atomic membership probe: drop() evicts the
                    # blob cache under _guard_lock strictly after
                    # popping `where`, so a stale read here only skips
                    # an adoption the eviction would have undone
                    if key not in self.where:
                        continue
                    prev = self._snap_cache.get(key)
                    if prev is None or prev[0] <= gens[key]:
                        self._snap_cache[key] = (gens[key], chunk_chars,
                                                 snap)
                    self.last_summarized_gen[key] = max(
                        self.last_summarized_gen.get(key, 0), gens[key])
            increment("summarize.dirty_docs", len(lanes))
            increment("summarize.blob_cache.misses", len(lanes))
        return out

    def extract_all(self, chunk_chars: int = 10000,
                    only: Optional[set] = None) -> Dict[tuple, dict]:
        jobs, cached = self.extract_dispatch(only, chunk_chars)
        return self.extract_assemble(jobs, chunk_chars, cached)

    # -- queries -----------------------------------------------------------
    def text(self, key: tuple) -> Optional[str]:
        """Materialized text for a channel: None if opaque/unknown, or
        when extraction hits non-text payloads (item sequences read via
        entries(); an items lane with no visible payloads reads as "" —
        exactly what a text view of it would say)."""
        if key not in self.where:
            return None
        from ..mergetree.host import NonTextPayload

        b, lane = self.where[key]
        row = self.pages.row(key) if self.paged \
            else self.buckets[b].row(lane)
        try:
            return extract_text(row, self.payloads)
        except NonTextPayload:  # items/run lane: not a text channel
            return None

    def entries(self, key: tuple) -> Optional[list]:
        """Full-fidelity snapshot entries for one lane (host gather of a
        single row — read path for composite channels like matrix axes,
        whose payloads are runs rather than text)."""
        from ..mergetree.catchup import extract_entries

        if key not in self.where:
            return None
        b, lane = self.where[key]
        row = self.pages.row(key) if self.paged \
            else self.buckets[b].row(lane)
        return extract_entries(row, self.payloads,
                               int(np.asarray(row.min_seq)))

    def lane_count(self) -> int:
        return len(self.where)


# ---------------------------------------------------------------------------
# LWW lanes: map/cell/counter channels on device (server/lww_kernel.py)
# ---------------------------------------------------------------------------

_CELL_KEY = "\x00cell"  # SharedCell = a one-key LWW map

# SharedMatrix serving lanes: one matrix channel materializes as TWO merge
# lanes (the permutation axes ARE merge-tree clients — reference
# packages/dds/matrix/src/permutationvector.ts:126) plus one LWW lane for
# the sparse cell store keyed by stable (row_id|col_id). The sub-lanes key
# under suffixed channel names ("\x00" cannot appear in real channel ids).
MATRIX_ROWS_SUFFIX = "\x00mx:rows"
MATRIX_COLS_SUFFIX = "\x00mx:cols"
MATRIX_CELLS_SUFFIX = "\x00mx:cells"
# SparseMatrix extends SharedMatrix (same wire shapes), so both types
# seed/compose through the matrix lanes.
_MATRIX_TYPES = {
    "https://graph.microsoft.com/types/sharedmatrix",
    "https://graph.microsoft.com/types/mergeTree/sparse-matrix",
}


_MATRIX_SUFFIXES = ((MATRIX_ROWS_SUFFIX, "rows"),
                    (MATRIX_COLS_SUFFIX, "cols"),
                    (MATRIX_CELLS_SUFFIX, "cells"))


def matrix_base_key(key: tuple) -> Optional[tuple]:
    """(doc, store, chan+suffix) -> (doc, store, chan) for matrix
    sub-lane keys; None for ordinary channel keys."""
    chan = key[2]
    if isinstance(chan, str) and "\x00mx:" in chan:
        for suffix, _ in _MATRIX_SUFFIXES:
            if chan.endswith(suffix):
                return (key[0], key[1], chan[:-len(suffix)])
    return None


def lane_base_key(key: tuple) -> Optional[tuple]:
    """Base channel key for ANY composite sub-lane (matrix axes/cells,
    directory); None for ordinary channels. Sub-lanes of one channel
    must version and persist atomically (local_server incremental
    summaries group by this)."""
    base = matrix_base_key(key)
    if base is not None:
        return base
    chan = key[2]
    if isinstance(chan, str) and chan.endswith(DIR_SUFFIX):
        return (key[0], key[1], chan[:-len(DIR_SUFFIX)])
    return None


def _compose_matrix_channels(out: Dict[tuple, dict]) -> None:
    """Recombine suffixed matrix sub-lane snapshots into ONE channel
    snapshot per matrix, keyed by the real channel name: the two axis
    snapshots in dds/matrix.py load_core's blob format (segments with
    wire-encoded runs, pre-encoded by extract_assemble) + the sparse
    cell map. Mutates `out` in place."""
    groups: Dict[tuple, Dict[str, dict]] = {}
    for key in [k for k in out
                if isinstance(k[2], str) and "\x00mx:" in k[2]]:
        for suffix, name in _MATRIX_SUFFIXES:
            if key[2].endswith(suffix):
                base = (key[0], key[1], key[2][:-len(suffix)])
                groups.setdefault(base, {})[name] = out.pop(key)
                break
    for base, parts in groups.items():
        composed: Dict[str, Any] = {
            "header": {"kind": "matrix", "sequenceNumber": 0}}
        seq = 0
        for axis in ("rows", "cols"):
            part = parts.get(axis)
            if part is None:
                composed[axis] = {"segments": [], "seq": 0, "minSeq": 0}
                continue
            hdr = part["header"]
            # Chunks arrive already wire-encoded (extract_assemble owns
            # the payload wire format).
            composed[axis] = {
                "segments": [e for chunk in part["chunks"]
                             for e in chunk],
                "seq": hdr["sequenceNumber"],
                "minSeq": hdr["minimumSequenceNumber"],
            }
            seq = max(seq, hdr["sequenceNumber"])
        cells = parts.get("cells")
        composed["cells"] = dict(cells["entries"]) if cells else {}
        if cells:
            seq = max(seq, cells["header"]["sequenceNumber"])
        composed["header"]["sequenceNumber"] = seq
        out[base] = composed


# SharedDirectory serving lane: the whole nested tree rides ONE LWW lane
# with (path, key) pairs interned as composite keys (path "\x1e" key —
# paths cannot contain the separator: subdirectory creates with such
# names degrade the channel), plus a host-tracked set of existing paths
# that gates storage ops exactly like the object path's
# get_working_directory drop (reference packages/dds/map/src/
# directory.ts:1624 subdirectory-scoped storage ops).
DIR_SUFFIX = "\x00dir"
DIR_SEP = "\x1e"
_DIRECTORY_TYPE = "https://graph.microsoft.com/types/directory"


def directory_route(op: Any) -> Optional[str]:
    """Classify a SharedDirectory wire op (dds/directory.py submit
    shapes): 'storage' / 'createSubDirectory' / 'deleteSubDirectory',
    None for anything else."""
    if not isinstance(op, dict):
        return None
    t = op.get("type")
    if t == "storage" and isinstance(op.get("path"), str) \
            and isinstance(op.get("op"), dict):
        return "storage"
    if t in ("createSubDirectory", "deleteSubDirectory") \
            and isinstance(op.get("path"), str) \
            and isinstance(op.get("name"), str):
        return t
    return None


def _child_path(parent: str, name: str) -> str:
    return parent.rstrip("/") + "/" + name


def _norm_path(path: str) -> str:
    """Canonical form matching SharedDirectory.get_working_directory's
    resolution (empty segments skipped): '/sub/', '//sub' -> '/sub';
    '', '/' -> '/'."""
    parts = [p for p in path.strip("/").split("/") if p]
    return "/" + "/".join(parts) if parts else "/"


def _flatten_directory(data: dict):
    """root.to_dict() nested form -> ({composite_key: value}, {paths}).
    Raises ValueError on separator-bearing subdirectory names."""
    entries: Dict[str, Any] = {}
    paths = set()

    def walk(node, path):
        if not isinstance(node, dict):
            raise ValueError("malformed directory node")
        paths.add(path)
        storage = node.get("storage", {})
        if not isinstance(storage, dict):
            raise ValueError("malformed directory storage")
        for k, v in storage.items():
            entries[path + DIR_SEP + k] = v
        subs = node.get("subdirectories", {})
        if not isinstance(subs, dict):
            raise ValueError("malformed subdirectories")
        for name, sub in subs.items():
            if DIR_SEP in name:
                raise ValueError("separator in subdirectory name")
            walk(sub, _child_path(path, name))

    walk(data, "/")
    return entries, paths


def _nest_directory(entries: Dict[str, Any], paths) -> dict:
    """Inverse of _flatten_directory: lane entries + path set ->
    root.to_dict() nested form."""
    nodes = {"/": {"storage": {}, "subdirectories": {}}}
    for p in sorted(paths, key=len):
        if p == "/" or p in nodes:
            continue
        parent, _, name = p.rpartition("/")
        parent = parent or "/"
        node = {"storage": {}, "subdirectories": {}}
        if parent in nodes:
            nodes[parent]["subdirectories"][name] = node
            nodes[p] = node
    for comp, v in entries.items():
        path, sep, key = comp.partition(DIR_SEP)
        if sep and path in nodes:
            nodes[path]["storage"][key] = v
    return nodes["/"]


def matrix_route(op: Any) -> Optional[str]:
    """Classify a SharedMatrix wire op (dds/matrix.py submit shapes):
    'rows'/'cols' for axis merge ops, 'cell' for cell writes, None for
    anything else."""
    from ..mergetree.catchup import looks_like_merge_op as _merge

    if not isinstance(op, dict):
        return None
    target = op.get("target")
    if target in ("rows", "cols") and _merge(op.get("op")):
        return target
    if target == "cell" and isinstance(op.get("key"), str):
        return "cell"
    return None


def looks_like_lww_op(op: Any) -> bool:
    if not isinstance(op, dict):
        return False
    t = op.get("type")
    if t in ("set", "delete"):
        # MapKernel ops always carry a pid; requiring it keeps shape-alike
        # ops from other DDSes out of the LWW lanes.
        return isinstance(op.get("key"), str) and "pid" in op
    if t == "clear":
        return "pid" in op  # ink's clear has no pid; directory's has a path
    if t == "increment":
        return "delta" in op
    return t in ("setCell", "deleteCell")


class _LwwBucket:
    """A batch of LWW lanes sharing one key-slot capacity (mirrors
    _MergeBucket: per-capacity buckets instead of one global table, so one
    hot channel cannot inflate device memory for every lane)."""

    def __init__(self, lk, capacity: int, lanes: int = 8):
        self.lk = lk
        self.capacity = capacity
        self.lanes = lanes
        self.state = lk.make_lww_state(capacity, batch=lanes)
        self.used: List[Optional[tuple]] = [None] * lanes
        self._blank_row = None  # built lazily, reused across frees
        self._free: List[int] = []
        self._next = 0
        self.placer = None  # optional dp-mesh placement callable
        # Upper bound of each lane's occupied key slots (the donation
        # gate's host-side fit proof; see _MergeBucket.count_hint for
        # the confirmed-base / in-flight-pending split).
        self.count_hint = np.zeros(lanes, np.int64)
        self.hint_pending = np.zeros(lanes, np.int64)

    def grow(self) -> None:
        old = self.lanes
        grown = self.lk.make_lww_state(self.capacity, batch=old * 2)
        self.state = jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s), grown, self.state)
        self.used.extend([None] * old)
        self.lanes = old * 2
        self.count_hint = np.concatenate(
            [self.count_hint, np.zeros(old, np.int64)])
        self.hint_pending = np.concatenate(
            [self.hint_pending, np.zeros(old, np.int64)])
        if self.placer is not None:
            self.state = self.placer(self.state)

    def alloc(self, key: tuple) -> int:
        # Free-list + frontier (see _MergeBucket.alloc).
        if self._free:
            i = self._free.pop()
        else:
            if self._next >= self.lanes:
                self.grow()
            i = self._next
            self._next += 1
        self.used[i] = key
        self.count_hint[i] = 0  # freed/frontier lanes are blank rows
        return i

    def free(self, lane: int) -> None:
        # Zero on free: reused lanes must not expose the previous
        # channel's keys/values (see _MergeBucket.free).
        self.used[lane] = None
        self._free.append(lane)
        self.hint_pending[lane] = 0
        if self._blank_row is None:
            self._blank_row = self.lk.make_lww_state(self.capacity)
        self.put_row(lane, self._blank_row, count_hint=0)

    def row(self, lane: int):
        return jax.tree_util.tree_map(lambda x: x[lane], self.state)

    def put_row(self, lane: int, row, count_hint: Optional[int] = None) \
            -> None:
        self.state = jax.tree_util.tree_map(
            lambda b, r: b.at[lane].set(r), self.state, row)
        self.count_hint[lane] = self.capacity if count_hint is None \
            else count_hint


class LwwLaneStore:
    """Device-resident LWW channel lanes + host key/value interning: the
    map/cell/counter half of server-side materialization (mapKernel.ts:490
    remote-apply semantics, batched across channels). Lanes live in
    key-capacity buckets; a lane whose key set outgrows its bucket promotes
    to the next one and its window re-applies from the retained pre-state."""

    def __init__(self, capacities: Tuple[int, ...] = (64, 1024, 16384),
                 lanes_per_bucket: int = 8,
                 t_buckets: Tuple[int, ...] = DEFAULT_T_BUCKETS):
        from . import lww_kernel as lk

        self.lk = lk
        self.capacities = tuple(capacities)
        self.t_buckets = tuple(t_buckets)
        self.buckets = [_LwwBucket(lk, c, lanes_per_bucket)
                        for c in self.capacities]
        self.where: Dict[tuple, Tuple[int, int]] = {}
        self.opaque: set = set()  # channels dropped after bucket exhaustion
        self.overflow_drops = 0
        self.change_gen: Dict[tuple, int] = {}  # see MergeLaneStore
        self._gen_counter = 0
        self.key_ids: Dict[str, int] = {}
        self.key_names: List[str] = []
        self.values: List[Any] = []  # payload refs -> raw (encoded) values
        self.windows_since_value_compact = 0
        self.value_compact_every = 64

    def intern_key(self, key: str) -> int:
        if key not in self.key_ids:
            self.key_ids[key] = len(self.key_names)
            self.key_names.append(key)
        return self.key_ids[key]

    def add_value(self, value: Any) -> int:
        self.values.append(value)
        return len(self.values) - 1

    def add_value_block(self, block: "_LwwValueBlock") -> int:
        """Register a whole flush's values at once (fast-path ingest);
        value id = base + block-local index, decoded lazily."""
        import itertools
        base = len(self.values)
        block.base = base
        self.values.extend(itertools.repeat(block, len(block)))
        return base

    def value(self, vid: int) -> Any:
        v = self.values[vid]
        return v.resolve(vid) if type(v) is _LwwValueBlock else v

    def lane_for(self, key: tuple) -> Tuple[int, int]:
        if key not in self.where:
            lane = self.buckets[0].alloc(key)
            self.where[key] = (0, lane)
        return self.where[key]

    def mark_dirty(self, key: tuple) -> None:
        self._gen_counter += 1
        self.change_gen[key] = self._gen_counter

    def drop(self, key: tuple) -> None:
        """Degrade a channel to opaque (unmodelable content): its device
        lane is abandoned (mirrors MergeLaneStore.drop)."""
        self.opaque.add(key)
        if key in self.where:
            b, lane = self.where.pop(key)
            self.buckets[b].free(lane)

    def seed(self, key: tuple, kind: str, header: Any) -> bool:
        """Bootstrap a lane from a summary header (map entries / cell
        value / counter accumulator) as synthetic seq-0 ops — any real op
        (seq >= 1) wins LWW over the seeded base."""
        lk = self.lk
        if key in self.where:
            return True
        if key in self.opaque:
            return False
        ops: List[tuple] = []
        try:
            if kind == "map" and isinstance(header, dict):
                for k, v in header.items():
                    ops.append((lk.LwwKind.SET, self.intern_key(k),
                                self.add_value(v), 0, 0))
            elif kind == "cell" and isinstance(header, dict):
                if header.get("hasValue"):
                    ops.append((lk.LwwKind.SET, self.intern_key(_CELL_KEY),
                                self.add_value(header.get("value")), 0, 0))
            elif kind == "counter" and isinstance(header, dict):
                delta = int(header.get("value", 0))
                if not (-2**31 <= delta < 2**31):
                    raise ValueError("counter base exceeds int32")
                if delta:
                    ops.append((lk.LwwKind.ADD, -1, -1, delta, 0))
            else:
                raise ValueError(f"unseedable header kind {kind!r}")
        except (ValueError, TypeError):
            # Unrepresentable base: materializing live ops over an EMPTY
            # base would serve wrong state — degrade to opaque instead.
            self.opaque.add(key)
            return False
        if ops:
            self.apply({key: ops})
            if key in self.opaque:
                return False  # oversized snapshot: degraded, not fatal
        else:
            self.lane_for(key)  # empty base: allocate so snapshots report
            self.mark_dirty(key)
        return True

    def wire_to_op(self, op: dict, seq: int) -> tuple:
        """(kind, key_id, val_id, delta, seq) for one sequenced wire op.
        Raises Unmodelable (never anything else) for content the kernel
        cannot represent — a malformed op must not crash-loop the
        partition (flush aborts before checkpointing, replay redelivers)."""
        lk = self.lk
        t = op.get("type")
        try:
            if t == "set":
                return (lk.LwwKind.SET, self.intern_key(op["key"]),
                        self.add_value(op.get("value")), 0, seq)
            if t == "delete":
                return (lk.LwwKind.DELETE, self.intern_key(op["key"]), -1,
                        0, seq)
            if t == "clear":
                return (lk.LwwKind.CLEAR, -1, -1, 0, seq)
            if t == "setCell":
                return (lk.LwwKind.SET, self.intern_key(_CELL_KEY),
                        self.add_value(op.get("value")), 0, seq)
            if t == "deleteCell":
                return (lk.LwwKind.DELETE, self.intern_key(_CELL_KEY), -1,
                        0, seq)
            if t == "increment":
                delta = int(op["delta"])
                if not (-2**31 <= delta < 2**31):
                    raise Unmodelable("increment delta exceeds int32")
                return (lk.LwwKind.ADD, -1, -1, delta, seq)
        except Unmodelable:
            raise
        except Exception as err:  # noqa: BLE001 — malformed wire content
            raise Unmodelable(f"malformed lww op: {err}") from err
        raise Unmodelable(f"unknown lww op {t!r}")

    def apply(self, streams: Dict[tuple, List[tuple]]) -> None:
        """streams: lane_key -> [(kind, key_id, val_id, delta, seq)].
        Windows chunk to the largest T bucket."""
        max_t = self.t_buckets[-1]
        while streams:
            window = {k: v[:max_t] for k, v in streams.items() if v}
            streams = {k: v[max_t:] for k, v in streams.items()
                       if len(v) > max_t}
            if window:
                self._apply_window(window)
        self.windows_since_value_compact += 1
        if self.windows_since_value_compact >= self.value_compact_every:
            self.compact_values()

    def _pack(self, lanes_count: int, window_lanes: Dict[int, List[tuple]],
              t: int):
        cols = {f: np.zeros((lanes_count, t), np.int32)
                for f in ("kind", "key", "val", "delta", "seq")}
        for lane, ops in window_lanes.items():
            for i, (kind, kid, vid, delta, seq) in enumerate(ops):
                cols["kind"][lane, i] = kind
                cols["key"][lane, i] = kid
                cols["val"][lane, i] = vid
                cols["delta"][lane, i] = delta
                cols["seq"][lane, i] = seq
        return self.lk.LwwOps(**{f: jnp.asarray(cols[f]) for f in cols})

    def _apply_window(self, window: Dict[tuple, List[tuple]]) -> None:
        per_bucket: Dict[int, Dict[int, List[tuple]]] = {}
        for key, ops in window.items():
            if key in self.opaque:
                continue  # degraded channel: never re-admit
            b, lane = self.lane_for(key)
            self.mark_dirty(key)
            per_bucket.setdefault(b, {})[lane] = ops
        for b, lane_ops in sorted(per_bucket.items()):
            bucket = self.buckets[b]
            t = _bucket(max(len(v) for v in lane_ops.values()),
                        self.t_buckets)
            ops_dev = self._pack(bucket.lanes, lane_ops, t)
            pre = bucket.state
            new = self.lk.apply_lww_batched(pre, ops_dev)
            over = np.asarray(new.overflow)
            flagged = [i for i in range(bucket.lanes)
                       if over[i] and i in lane_ops]
            if flagged:
                idx = jnp.asarray(np.asarray(flagged, np.int32))
                new = jax.tree_util.tree_map(
                    lambda bcol, p: bcol.at[idx].set(p[idx]), new, pre)
            bucket.state = new
            # Each applied op can occupy at most one new key slot.
            for i, ops in lane_ops.items():
                bucket.count_hint[i] += len(ops)
            for i in flagged:
                self._promote(b, i, lane_ops[i], t)

    def _promote(self, b: int, lane: int, ops: List[tuple], t: int) -> None:
        """Overflowed lane: move to the next capacity bucket and re-apply
        its window from the retained pre-state row."""
        key = self.buckets[b].used[lane]
        row = self.buckets[b].row(lane)
        self.buckets[b].free(lane)
        for nb in range(b + 1, len(self.buckets)):
            target = self.buckets[nb]
            wide = self.lk.grow_lane_capacity(
                jax.tree_util.tree_map(lambda x: x[None], row),
                target.capacity)
            ops_dev = self._pack(1, {0: ops}, t)
            redone = self.lk.apply_lww_batched(wide, ops_dev)
            if not bool(np.asarray(redone.overflow)[0]):
                new_lane = target.alloc(key)
                target.put_row(new_lane, jax.tree_util.tree_map(
                    lambda x: x[0], redone))
                self.where[key] = (nb, new_lane)
                return
            row = jax.tree_util.tree_map(lambda x: x[0], wide)
        # Exhausted every key-capacity bucket: degrade this ONE channel to
        # opaque (no server-side materialization) instead of crashing the
        # pump — same discipline as the merge lanes, and it must hold for
        # client-authored summary seeds too (a crash here would loop on
        # every restart re-probe of the same stored summary).
        del self.where[key]
        self.opaque.add(key)
        self.overflow_drops += 1

    def compact_values(self) -> None:
        """Reclaim unreferenced payloads: memory must track LIVE state, not
        total op count (the merge side's zamboni analog for values)."""
        referenced: set = set()
        for bucket in self.buckets:
            if any(k is not None for k in bucket.used):
                vals = np.asarray(bucket.state.val)
                referenced.update(int(v) for v in np.unique(vals) if v >= 0)
        remap = {old: new for new, old in enumerate(sorted(referenced))}
        # Materialize through value(): block entries must decode before
        # the id space is renumbered (resolve() keys off the old base).
        self.values = [self.value(old) for old in sorted(referenced)]
        for bucket in self.buckets:
            if not any(k is not None for k in bucket.used):
                continue
            vals = np.asarray(bucket.state.val)
            # Exact key-slot occupancy refresh while the plane is on the
            # host anyway (donation-gate hints; see _MergeBucket).
            bucket.count_hint = np.count_nonzero(
                np.asarray(bucket.state.key) >= 0, axis=-1).astype(np.int64)
            out = np.full_like(vals, -1)
            for old, new in remap.items():
                out[vals == old] = new
            bucket.state = bucket.state._replace(val=jnp.asarray(out))
            if bucket.placer is not None:
                # Same dp-mesh rule as the merge side's major collection:
                # jnp.asarray dropped the placement; re-place.
                bucket.state = bucket.placer(bucket.state)
        self.windows_since_value_compact = 0

    # -- reads (tests / snapshots) -----------------------------------------
    def snapshot(self, lane_key: tuple) -> Optional[dict]:
        """Entries hold WIRE-ENCODED values (handles stay in their encoded
        dict form): the server has no runtime to bind live handles to —
        clients decode at load, exactly as they do for ops."""
        if lane_key not in self.where:
            return None
        b, lane = self.where[lane_key]
        state = self.buckets[b].state
        keys = np.asarray(state.key[lane])
        vals = np.asarray(state.val[lane])
        entries = {}
        for kid, vid in zip(keys, vals):
            if int(kid) >= 0:
                entries[self.key_names[int(kid)]] = (
                    self.value(int(vid)) if int(vid) >= 0 else None)
        return {
            "entries": entries,
            "counter": int(np.asarray(state.counter[lane])),
            "sequenceNumber": int(np.asarray(state.last_seq[lane])),
        }


class _LwwValueBlock:
    """One flush's LWW values as raw JSON spans of the retained wire
    buffers, decoded lazily (and cached) at read time — snapshots touch a
    handful of values; the ingest path touches none."""

    __slots__ = ("base", "bufs", "vbuf", "vstart", "vend", "_cache")

    def __init__(self, bufs, vbuf, vstart, vend):
        self.base = -1  # assigned by LwwLaneStore.add_value_block
        self.bufs = bufs
        self.vbuf = vbuf
        self.vstart = vstart
        self.vend = vend
        self._cache: Dict[int, Any] = {}

    def __len__(self) -> int:
        return len(self.vbuf)

    def resolve(self, vid: int) -> Any:
        i = vid - self.base
        if i in self._cache:
            return self._cache[i]
        s = int(self.vstart[i])
        v = None if s < 0 else json.loads(
            self.bufs[int(self.vbuf[i])][s:int(self.vend[i])])
        self._cache[i] = v
        return v


def _pack_lane_runs(lanes, kind, client, ref, pos1, length, K, run_min):
    """Vectorized insert-run detection over ONE flush's merge rows
    (oppack.pack_run_slots semantics, numpy over pump columns).

    Rows arrive in stream order with lanes interleaved; runs are
    CONSECUTIVE same-lane INSERT rows by one client at ONE refSeq whose
    positions chain as append (pos_{i+1} == pos_i + len_i) or prepend
    (pos_{i+1} == pos_i) — equal refs make the packed single-perspective
    apply exact (any in-window foreign seq is > every member's ref, so
    classification is identical at all members' perspectives). Runs of
    >= run_min members chunk into INSERT_RUN slots of up to K; prepend
    slots lay their members out REVERSED (each later prepend lands
    before its predecessor, exactly the scalar tie-break order).

    Returns per-row arrays:
      slot       int  — the row's op slot within its lane
      sub        int  — layout index within the slot (-1 = plain op)
      head       bool — stream-first member of a run slot (provides the
                        slot's pos1/ref/client columns)
      tail       bool — stream-last member (provides the slot's
                        doc_lane/t_idx, i.e. its seq/msn gather source)
    Plain rows have head == tail == True and sub == -1."""
    n = len(lanes)
    if n == 0:
        z = np.zeros(0, np.int64)
        return z, z - 1, np.zeros(0, bool), np.zeros(0, bool)
    idx = np.arange(n)
    order = np.lexsort((idx, lanes))  # lane-grouped, stream-ordered
    la, ki, cl, rf, p1, ln = (a[order] for a in
                              (lanes, kind, client, ref, pos1, length))
    ins = (ki == OpKind.INSERT) & (ln > 0)
    same = np.zeros(n, bool)
    same[1:] = ((la[1:] == la[:-1]) & ins[1:] & ins[:-1]
                & (cl[1:] == cl[:-1]) & (rf[1:] == rf[:-1]))
    app = np.zeros(n, bool)
    pre = np.zeros(n, bool)
    app[1:] = same[1:] & (p1[1:] == p1[:-1] + ln[:-1])
    pre[1:] = same[1:] & (p1[1:] == p1[:-1])
    link = np.where(app, 1, np.where(pre, 2, 0))
    # A link continues the run only if it matches the run's first link
    # type; since consecutive links must agree pairwise for a uniform
    # chain, "same type as previous link" suffices (the first link sets
    # the type; a type flip breaks).
    cont = link > 0
    cont[2:] &= (link[2:] == link[1:-1]) | (link[1:-1] == 0)
    start = ~cont
    run_id = np.cumsum(start) - 1
    # Member position within the run, run sizes.
    q = idx - np.maximum.accumulate(np.where(start, idx, 0))
    run_sizes = np.bincount(run_id, minlength=run_id[-1] + 1)
    size_of = run_sizes[run_id]
    # Runs below run_min (or singletons) stay plain.
    member = size_of >= run_min
    # Chunk runs into slots of K.
    slot_in_run = q // K
    sub_stream = q % K
    # Per-slot member count (last chunk may be short). A remainder
    # chunk below run_min is not worth a padded slot: demote to plain
    # (pack_run_slots does the same).
    chunk = np.minimum(size_of - slot_in_run * K, K)
    member = member & (chunk >= run_min)
    run_type = np.zeros(n, np.int64)
    # type of the run = type of its second element's link (first link).
    first_link_idx = np.maximum.accumulate(np.where(start, idx, 0)) + 1
    valid_fl = first_link_idx < n
    fl = np.where(valid_fl, np.minimum(first_link_idx, n - 1), n - 1)
    run_type = np.where(member, link[fl], 0)
    sub = np.where(run_type == 2, chunk - 1 - sub_stream, sub_stream)
    sub = np.where(member, sub, -1)
    # Slot numbering within the lane: plain rows and stream-first chunk
    # members start a slot.
    starts_slot = ~member | (sub_stream == 0)
    # cumcount of slot starts per lane (rows already lane-grouped).
    lane_start = np.zeros(n, bool)
    lane_start[0] = True
    lane_start[1:] = la[1:] != la[:-1]
    slot_cum = np.cumsum(starts_slot)
    adj = np.maximum.accumulate(
        np.where(lane_start, slot_cum - starts_slot.astype(np.int64), 0))
    slot_sorted = slot_cum - 1 - adj
    head = ~member | (sub_stream == 0)
    tail = ~member | (sub_stream == chunk - 1)
    # Map back to original row order.
    slot = np.empty(n, np.int64)
    sub_o = np.empty(n, np.int64)
    head_o = np.empty(n, bool)
    tail_o = np.empty(n, bool)
    slot[order] = slot_sorted
    sub_o[order] = sub
    head_o[order] = head
    tail_o[order] = tail
    return slot, sub_o, head_o, tail_o


def _cumcount(groups: np.ndarray) -> np.ndarray:
    """Per-row occurrence index within its group value, preserving row
    order (vectorized groupby-cumcount)."""
    n = len(groups)
    if n == 0:
        return np.zeros(0, np.int64)
    order = np.argsort(groups, kind="stable")
    sg = groups[order]
    starts = np.flatnonzero(np.r_[True, sg[1:] != sg[:-1]])
    counts = np.diff(np.r_[starts, n])
    pos_sorted = np.arange(n) - np.repeat(starts, counts)
    out = np.empty(n, np.int64)
    out[order] = pos_sorted
    return out


class SequencedWindow:
    """One fast flush's admitted messages, materialized lazily.

    The slow path produces one SequencedDocumentMessage per op at flush
    time; the fast path hands downstream ONE window per flush (the
    reference's per-message kafka produce batched per flush window) and
    builds message objects only when a consumer iterates. Columns are
    numpy views over the flush's pump output; payload JSON stays in the
    retained wire buffers until touched."""

    def __init__(self, bufs: List[bytes], doc_ids: List[str],
                 ordinals: List[Dict[int, str]], rows: np.ndarray,
                 cols: np.ndarray, seqs: np.ndarray, msns: np.ndarray):
        self.bufs = bufs
        self.doc_ids = doc_ids          # row index -> document id
        self.ordinals = ordinals        # row index -> ordinal->client map
        self.rows = rows                # row indices into cols (in order)
        self.cols = cols
        self.seqs = seqs                # per-row assigned seq (0 = dropped)
        self.msns = msns

    def __len__(self) -> int:
        return int((self.seqs > 0).sum())

    def messages(self):
        """Yield (doc_id, SequencedDocumentMessage) for every admitted
        message, per-document order preserved."""
        from . import pump as P
        from .wire import document_message_from_dict
        c = self.cols
        for j, row in enumerate(self.rows.tolist()):
            seq = int(self.seqs[j])
            if seq <= 0:
                continue
            buf = self.bufs[int(c[P.BUF, row])]
            msg = document_message_from_dict(json.loads(
                buf[int(c[P.MSTART, row]):int(c[P.MEND, row])]))
            client_id = None
            if int(c[P.KIND, row]) == tk.MsgKind.OP:
                client_id = self.ordinals[j].get(int(c[P.CLIENT, row]))
            out = SequencedDocumentMessage.from_document_message(
                msg, client_id, seq, int(self.msns[j]))
            out.traces.append(ITrace.now("deli", "sequence"))
            yield self.doc_ids[j], out


# ---------------------------------------------------------------------------
# the lambda
# ---------------------------------------------------------------------------

class _DocLane:
    """Host bookkeeping for one document's device lane."""

    def __init__(self, lane: int):
        self.lane = lane
        self.interner: Dict[str, int] = {}   # wire client id -> ordinal
        self.ordinals: Dict[int, str] = {}
        self.log_offset = -1
        self.next_ordinal = 0
        # Host mirror of live membership + last activity, for ghost-client
        # eviction (not persisted; _restore re-stamps from the device
        # client table). `evicting` dedups in-flight synthesized leaves.
        self.last_seen: Dict[str, float] = {}
        self.evicting: set = set()

    def intern(self, client_id: str) -> int:
        if client_id not in self.interner:
            self.interner[client_id] = self.next_ordinal
            self.ordinals[self.next_ordinal] = client_id
            self.next_ordinal += 1
        return self.interner[client_id]

    def dump(self) -> dict:
        return {"lane": self.lane, "logOffset": self.log_offset,
                "interner": dict(self.interner),
                "nextOrdinal": self.next_ordinal}

    @staticmethod
    def load(d: dict) -> "_DocLane":
        dl = _DocLane(d["lane"])
        dl.log_offset = d["logOffset"]
        dl.interner = {k: int(v) for k, v in d["interner"].items()}
        dl.ordinals = {v: k for k, v in dl.interner.items()}
        dl.next_ordinal = d["nextOrdinal"]
        return dl


class _Pending:
    """One parsed, not-yet-flushed message."""

    __slots__ = ("kind", "ordinal", "client_seq", "ref_seq", "msg",
                 "client_id")

    def __init__(self, kind: int, ordinal: int, client_seq: int,
                 ref_seq: int, msg: DocumentMessage,
                 client_id: Optional[str]):
        self.kind = kind
        self.ordinal = ordinal
        self.client_seq = client_seq
        self.ref_seq = ref_seq
        self.msg = msg
        self.client_id = client_id


class _SummaryProbe:
    """Parsed channel snapshots from a document's stored summary:
    sequence_number (the summary's protocol seq) + per-(store, channel)
    merge-tree seed payloads (entries, minSeq, seq) and LWW seed payloads
    (kind, header-data)."""

    def __init__(self, sequence_number: int,
                 channels: Dict[Tuple[str, str], tuple],
                 lww_channels: Optional[Dict[Tuple[str, str],
                                             tuple]] = None,
                 dir_paths: Optional[Dict[Tuple[str, str], set]] = None):
        self.sequence_number = sequence_number
        self.channels = channels
        self.lww_channels = lww_channels or {}
        # (store, chan+DIR_SUFFIX) -> existing-path set for directory
        # channels (seeded alongside the flattened LWW entries).
        self.dir_paths = dir_paths or {}


# Channel types the LWW lanes can seed from a summary header.
_LWW_SEED_TYPES = {
    "https://graph.microsoft.com/types/map": "map",
    "https://graph.microsoft.com/types/cell": "cell",
    "https://graph.microsoft.com/types/counter": "counter",
}


def _parse_summary_probe(tree) -> Optional[_SummaryProbe]:
    """Walk a container summary (".protocol" blob + ".app" store trees)
    and extract every chunked merge-tree channel body (sequence
    summarize_core format: header {seq, minSeq, chunkCount} + body_i)."""
    import json as _json
    proto = tree.entries.get(".protocol")
    app = tree.entries.get(".app")
    if proto is None or app is None or not hasattr(app, "entries"):
        return None
    try:
        seq = int(_json.loads(proto.content).get("sequenceNumber", 0))
    except (ValueError, TypeError, AttributeError):
        # Client-authored content: malformed protocol blob => no seeding,
        # never a pump crash.
        return None
    stores = app.entries.get(".dataStores")
    if stores is None or not hasattr(stores, "entries"):
        return None
    channels: Dict[Tuple[str, str], tuple] = {}
    lww_channels: Dict[Tuple[str, str], tuple] = {}
    dir_paths: Dict[Tuple[str, str], set] = {}
    for store_id, store_tree in stores.entries.items():
        if not hasattr(store_tree, "entries"):
            continue
        channel_root = store_tree.entries.get(".channels", store_tree)
        if not hasattr(channel_root, "entries"):
            continue
        for channel_id, node in channel_root.entries.items():
            if not hasattr(node, "entries"):
                continue
            # A malformed .attributes blob must not cost a channel its
            # merge seeding — classification just falls back to "".
            ctype = ""
            attrs = node.entries.get(".attributes")
            if attrs is not None:
                try:
                    ctype = _json.loads(attrs.content).get("type", "")
                except (ValueError, TypeError, AttributeError):
                    ctype = ""
            if ctype in _MATRIX_TYPES:
                # Matrix snapshots (dds/matrix.py summarize_core): two
                # axis snapshots seed merge lanes under suffixed names,
                # the cells blob seeds the LWW cell-store lane. Parsed
                # into locals FIRST and committed atomically: a malformed
                # blob must skip the WHOLE matrix (a partially seeded
                # matrix would serve axes inconsistent with its cells).
                try:
                    axis_payloads = {}
                    for blob, suffix in (("rows", MATRIX_ROWS_SUFFIX),
                                         ("cols", MATRIX_COLS_SUFFIX)):
                        snap = _json.loads(node.entries[blob].content)
                        axis_payloads[suffix] = (
                            snap["segments"], int(snap.get("minSeq", 0)),
                            int(snap.get("seq", 0)))
                    cells = _json.loads(node.entries["cells"].content)
                    if not isinstance(cells, dict):
                        raise ValueError("cells blob is not a map")
                except (ValueError, TypeError, KeyError, AttributeError):
                    continue  # malformed client channel: skip, don't crash
                for suffix, payload in axis_payloads.items():
                    channels[(store_id, channel_id + suffix)] = payload
                lww_channels[(store_id,
                              channel_id + MATRIX_CELLS_SUFFIX)] = (
                    "map", cells)
                continue
            if ctype == _DIRECTORY_TYPE:
                # Directory snapshots (dds/directory.py summarize_core):
                # the nested tree flattens into one LWW seed + the
                # existing-path set that gates storage ops.
                lane_name = channel_id + DIR_SUFFIX
                try:
                    data = _json.loads(node.entries["header"].content)
                    entries, paths = _flatten_directory(data)
                except (ValueError, TypeError, KeyError, AttributeError):
                    # Unflattenable snapshot (separator-bearing names,
                    # malformed tree): DEGRADE the lane, don't skip —
                    # a fresh empty lane would silently serve a tree
                    # missing the snapshot content. The unknown seed
                    # kind makes lww.seed mark the channel opaque.
                    lww_channels[(store_id, lane_name)] = (
                        "unmodelable-directory", None)
                    continue
                lww_channels[(store_id, lane_name)] = ("map", entries)
                dir_paths[(store_id, lane_name)] = paths
                continue
            if "header" not in node.entries:
                continue
            try:
                header = _json.loads(node.entries["header"].content)
                lww_kind = _LWW_SEED_TYPES.get(ctype)
                if lww_kind is not None:
                    lww_channels[(store_id, channel_id)] = (lww_kind,
                                                            header)
                    continue
                count = int(header.get("chunkCount", -1))
                if count < 0:
                    continue  # not a chunked merge-tree body
                entries: List[dict] = []
                for i in range(count):
                    entries.extend(_json.loads(
                        node.entries[f"body_{i}"].content))
                payload = (entries, int(header.get("minSeq", 0)),
                           int(header.get("seq", 0)))
            except (ValueError, TypeError, KeyError, AttributeError):
                continue  # malformed client channel: skip, don't crash
            channels[(store_id, channel_id)] = payload
    return _SummaryProbe(seq, channels, lww_channels, dir_paths)


class TpuSequencerLambda(IPartitionLambda):
    """Sequences a partition's documents on device (see module docstring).

    emit(document_id, SequencedDocumentMessage) and nack(document_id,
    client_id, Nack) have the exact DeliLambda contract, so this lambda is a
    drop-in for the scalar deli in any lambda host.
    """

    def __init__(self, context: LambdaContext,
                 emit: Callable[[str, SequencedDocumentMessage], None],
                 nack: Callable[[str, str, Nack], None],
                 lanes: int = 8, clients_capacity: int = 8,
                 checkpoints=None, deltas=None, fresh_log: bool = False,
                 materialize: bool = True,
                 merge_store: Optional[MergeLaneStore] = None,
                 t_buckets: Tuple[int, ...] = DEFAULT_T_BUCKETS,
                 storage=None, client_timeout_s: float = 300.0,
                 send_system=None, config=None, mesh=None,
                 paged_lanes: bool = False):
        """storage: optional callable doc_id -> SummaryTree | None (the
        historian's latest summary). Enables snapshot seeding: merge lanes
        for channels whose base content shipped in a summary bootstrap
        from it instead of overflowing on the first op.

        client_timeout_s: ghost-client eviction window (0 disables) —
        writers silent this long get a synthesized leave so they stop
        pinning the MSN (DeliLambda clientTimeout semantics). config (the
        same nconf slice DeliLambda takes) overrides it via
        deli.clientTimeoutMsec."""
        self.context = context
        self.emit = emit
        self.nack = nack
        self.checkpoints = checkpoints
        self.deltas = deltas
        self.storage = storage
        self.client_timeout_s = client_timeout_s
        if config is not None:
            configured = config.get("deli.clientTimeoutMsec", None)
            if configured is not None:
                # Override only when actually configured — an explicit
                # client_timeout_s argument survives an unrelated config.
                self.client_timeout_s = float(configured) / 1000.0
        # Eviction leaves ride the raw log when a producer is available
        # (replay-deterministic, DeliLambda semantics); fallback appends
        # to the in-memory backlog. _DocLane.evicting dedups in-flight.
        self.send_system = send_system
        # doc_id -> parsed summary probe result (None = no usable summary);
        # probed at most once per document per process.
        self._summary_probes: Dict[str, Optional["_SummaryProbe"]] = {}
        # Read-path catch-up watermarks (server/readpath.py): the max
        # change generation each document's PUBLISHED artifact covers.
        # Advanced only on confirmed publish (catchup_mark_published) —
        # a refresh whose protocol half was unavailable must retry, not
        # silently freeze the artifact at a stale epoch.
        self._catchup_gen: Dict[str, int] = {}
        # fresh_log=True: this lambda consumes a brand-new MessageLog (a
        # multi-node takeover hands over checkpointed state, not the log);
        # checkpointed offsets index the PREVIOUS core's log and must not
        # gate replay of the new one (DeliLambda fresh_log semantics).
        self.fresh_log = fresh_log
        self.t_buckets = tuple(t_buckets)
        # Multi-chip serving: with a mesh, the ticket lanes AND the
        # merge/LWW channel lanes shard over 'dp' — lanes are
        # embarrassingly parallel, so GSPMD partitions the whole fused
        # window with no inter-device traffic beyond the small ticket
        # gather (reference analog: one deli consumer per partition,
        # partitionManager.ts:22, collapsed onto one mesh).
        self.mesh = mesh
        if mesh is not None:
            # Lane counts must be dp-divisible to shard; doubling growth
            # preserves divisibility afterwards.
            dp = int(mesh.shape.get("dp", 1))
            lanes = ((max(lanes, dp) + dp - 1) // dp) * dp
        self.lanes = lanes
        self.k = clients_capacity
        self.tstate: tk.TicketState = self._place(
            tk.make_ticket_state(self.k, batch=lanes))
        self.docs: Dict[str, _DocLane] = {}
        self.pending: Dict[str, List[_Pending]] = {}
        self.materialize = materialize
        self.merge = merge_store if merge_store is not None else \
            MergeLaneStore(t_buckets=t_buckets, paged=paged_lanes,
                           mesh=mesh)
        self.lww = LwwLaneStore(t_buckets=t_buckets)
        if getattr(self.merge, "paged", False) and mesh is not None \
                and getattr(self.merge.pages, "mesh", None) is None:
            # An externally provided paged store must already carry the
            # mesh placement: the pool's dispatch selection (donate vs
            # keep — R6) is fixed at ITS construction, and silently
            # serving a single-chip pool under a mesh would re-donate a
            # sharded plane exactly where MESH_DONATION_GATE forbids it.
            raise ValueError(
                "paged merge_store was constructed without the mesh: "
                "pass mesh= to MergeLaneStore/PagedMergeStore so the "
                "pool places via partition_rules.POOL_PARTITION_RULES "
                "and dispatches through the non-donating variants "
                "(docs/serving_pipeline.md R6).")
        if mesh is not None:
            dp = int(mesh.shape.get("dp", 1))
            for bucket in self.merge.buckets + self.lww.buckets:
                # Grow to a dp multiple BEFORE placing (a 16-chip mesh
                # cannot shard the default 8 lanes).
                while bucket.lanes % dp != 0 or bucket.lanes < dp:
                    bucket.grow()
                bucket.placer = self._place
                bucket.state = self._place(bucket.state)
        self._pending_offset: Optional[int] = None
        # Fast-path (raw wire bytes) ingest state: the native pump + its
        # ordinal mirrors. emit_window, when set, receives ONE
        # SequencedWindow per fast flush instead of per-message emits.
        self.emit_window: Optional[Callable[[SequencedWindow], None]] = None
        self._raw_backlog: List[Tuple[int, str, bytes]] = []
        self.poison_frames = 0  # undecodable raw frames dropped (logged)
        self._raw_offsets: Dict[str, int] = {}
        # Pipelined mode (opt-in): clean fast windows defer their result
        # fetch/emit into a bounded FIFO ring of dispatched-but-unread
        # windows, so window k+1's host pack/staging overlaps window k's
        # device execution and window k-1's narrow readback. The ring
        # drains in dispatch order; anything lane-state-dependent (slow
        # windows, fold/rescue, payload GC, summarize extract) forces a
        # full drain first (docs/serving_pipeline.md).
        self.pipelined = False
        self.ring_depth = 4            # max dispatched-but-unread entries
        self.adaptive_window = True    # per-flush T/depth from latencies
        self._ring: "deque" = deque()
        # Fused serving bursts (docs/serving_pipeline.md R8): windows
        # whose occupancy-hint fit proofs pass stay STAGED (packed but
        # undispatched) and flush as ONE lax.scan program per
        # burst_depth windows (serve_step.serve_burst) — the last
        # per-window host round-trip (dispatch RPC + narrow readback)
        # amortizes over the whole burst. Requires lane-state donation
        # (the scan carry is donated), so dp meshes stay on the
        # per-window ring. Scan lengths draw from the fixed grid so the
        # burst program's compile cache stays bounded; a remainder of
        # one window dispatches through plain serve_window.
        self.fused_bursts = True
        self.burst_depth = 8           # staged windows per scan cap
        self._burst_k_grid = (2, 4, 8, 16, 32)
        self._staged: List[dict] = []  # packed-not-yet-dispatched windows
        # Whether this backend's jit call BLOCKS on execution (CPU) or
        # dispatches asynchronously (tpu/axon) — picks the _device_busy
        # signal that decides when staged windows stop accumulating.
        import jax as _jax
        self._dispatch_blocking = _jax.default_backend() not in (
            "tpu", "axon")
        # Overflow quarantine (mid-ring fold/rescue): channel ordinals
        # whose lanes were rolled back + host-recovered while later
        # windows were already in flight. Those windows' rows for these
        # channels re-apply host-side at their own drain; the sets clear
        # when the ring fully drains.
        self._ring_fixup: set = set()
        self._ring_fixup_lww: set = set()
        # Deferred GC cadence: compactions that came due while windows
        # were in flight (they move lanes, so they only run ring-empty).
        self._gc_due = False
        # Donation: provably-overflow-free windows dispatch through the
        # donating serve_window (lane states updated in place); windows
        # the occupancy hints cannot clear keep their pre states via
        # serve_window_keep for the fold/rescue rollback. Mesh placements
        # keep every window on serve_window_keep (ticket-state-only
        # donation): on jax 0.4.37 a donated dp-sharded lane-state list
        # reloaded from the persistent compilation cache returns corrupt
        # lane planes — cold compiles and non-donating reloads are both
        # correct, only the cache-hit donating executable miscompiles
        # (repro: tests/test_mesh_serving.py warm vs cold after
        # `rm -rf /tmp/fluid_tpu_xla_cache`). Revisit on a jax upgrade.
        self.donate_lane_states = mesh is None
        # Bumped by every fast-path fold/rescue/fixup: a flush's staged
        # lane placement is stale once this moves (re-resolve).
        self._recovery_gen = 0
        # Test/chaos hook: defer even hint-risky windows into the ring,
        # forcing the mid-ring quarantine fixup path that production
        # traffic only hits on unpredicted (overlap/anno-ring) overflow.
        # Donation still follows the gate — risky windows keep their pre
        # states, which the forced recovery then needs.
        self.defer_risky_windows = False
        # Fault-injection hook (testing/faultinject.py stall): called at
        # the top of every flush to model a slow device; None in
        # production.
        self.stall_hook: Optional[Callable[[], float]] = None
        # Insert-run packing on the fast path (PERF.md lever 3): typing
        # bursts in a window collapse to INSERT_RUN slots; a mispredicted
        # member admission (rare: dup/stale nack inside a run) flags the
        # lane and takes the standard overflow rollback + scalar re-run.
        self.pack_runs = True
        # Fused VMEM-resident merge apply inside the fast window (lazy
        # probe on first fast flush; scan kernel wherever Mosaic is
        # unavailable or a bucket exceeds the fused VMEM budget). Mesh
        # sharding keeps the scan path — the fused kernel is single-chip.
        self._fused_serve: Optional[bool] = False if mesh is not None \
            else None
        self._pump = None
        self._pump_ord: Dict[str, int] = {}     # doc id -> pump ordinal
        self._pump_synced: Dict[str, int] = {}  # doc id -> synced ordinals
        self._pump_known: set = set()
        # Docs the SLOW path interned clients into since the last fast
        # flush: only these re-sync into the pump per flush (the full
        # _pump_known sweep was O(docs) host work per flush even when
        # nothing changed).
        self._pump_sync_dirty: set = set()
        self._pump_docs: List[Optional[str]] = []   # pump ord -> doc id
        self._pump_lane = np.full(64, -1, np.int32)  # pump ord -> lane
        self._pump_chan: List[tuple] = []           # chan ord -> key tuple
        self._chan_ord: Dict[tuple, int] = {}       # key tuple -> chan ord
        self._lww_key_map = np.full(64, -1, np.int32)  # key ord -> kid
        # Directory lanes: lane key -> set of existing subdirectory paths
        # (host structure; rebuilt by replay, seeded from summaries).
        self._dir_paths: Dict[tuple, set] = {}
        # R10: the native pump runs paged too — paged fast flushes stage
        # page-group jobs and dispatch the serving megakernel
        # (serve_step.serve_megakernel), so there is no bucket-grid
        # dependency left in the hot path and no reason to gate the
        # toolchain on the storage layout.
        try:
            from . import pump as _pump_mod
            if _pump_mod.available():
                self._pump = _pump_mod.WirePump()
        except (ImportError, OSError, RuntimeError):
            # No toolchain: object path only. Counted so a fleet
            # that SHOULD be on the native pump shows the
            # regression on /healthz instead of just running slow.
            record_swallow("sequencer.pump_unavailable")
            self._pump = None
        # Megakernel fused-phase mode for paged rings on CPU backends:
        # False dispatches the scan op-phase INSIDE serve_megakernel
        # (still one device program per ring); True ("interpret") runs
        # the pallas megakernel body under the pallas interpreter so
        # tier-1 exercises the identical program the TPU lowers. The
        # TPU/axon probe (self._fused_serve) takes precedence.
        self.megakernel_interpret = False
        self._restore()

    # -- checkpoint/restore ------------------------------------------------
    def _restore(self) -> None:
        if self.checkpoints is None:
            return
        rows = list(self.checkpoints.find(
            lambda d: d.get("kind") == "tpu-sequencer"))
        if not rows:
            return
        dump = rows[0]["state"]
        self.docs = {doc: _DocLane.load(d)
                     for doc, d in dump["docs"].items()}
        if self.fresh_log:
            for dl in self.docs.values():
                dl.log_offset = -1
        cols = dump["tstate"]
        self.lanes = len(cols["next_seq"])
        self.k = len(cols["client_ids"][0]) if cols["client_ids"] else self.k
        self.tstate = self._place(tk.TicketState(
            client_ids=jnp.asarray(np.asarray(cols["client_ids"], np.int32)),
            client_ref=jnp.asarray(np.asarray(cols["client_ref"], np.int32)),
            client_cseq=jnp.asarray(np.asarray(cols["client_cseq"],
                                               np.int32)),
            next_seq=jnp.asarray(np.asarray(cols["next_seq"], np.int32)),
            min_seq=jnp.asarray(np.asarray(cols["min_seq"], np.int32)),
            overflow=jnp.asarray(np.asarray(cols["overflow"], np.bool_)),
        ))
        # Re-arm ghost eviction for members restored into the device
        # client table (last_seen is not persisted): a ghost present at
        # the crash still ages out after restart.
        now = time.time()
        ids = np.asarray(self.tstate.client_ids)
        for dl in self.docs.values():
            for ordinal in ids[dl.lane]:
                if int(ordinal) >= 0:
                    client = dl.ordinals.get(int(ordinal))
                    if client is not None:
                        dl.last_seen[client] = now
        self._rebuild_merge()

    def _probe_summary(self, doc_id: str) -> Optional[_SummaryProbe]:
        if doc_id in self._summary_probes:
            return self._summary_probes[doc_id]
        probe = None
        if self.storage is not None:
            try:
                tree = self.storage(doc_id)
            except Exception:  # noqa: BLE001 — storage backends vary
                # Miss = no seed (correct for fresh documents), but a
                # climbing rate means summaries exist and cannot be read
                # — catch-up is silently replaying whole logs.
                record_swallow("sequencer.summary_probe_miss")
                tree = None
            if tree is not None:
                probe = _parse_summary_probe(tree)
        self._summary_probes[doc_id] = probe
        if probe is not None and probe.sequence_number == 0:
            # Attach summary: NOTHING can predate seq 0, so eagerly seed
            # every channel — summary-only channels (never touched by a
            # live op) materialize for server-side reads too.
            for (store, channel), payload in probe.channels.items():
                self.merge.seed((doc_id, store, channel), *payload)
            for (store, channel), payload in probe.lww_channels.items():
                self._seed_lww((doc_id, store, channel), payload, probe)
        return probe

    def _seed_lww(self, key: tuple, payload: tuple,
                  probe: _SummaryProbe) -> bool:
        """lww.seed + directory path-set installation: a directory lane's
        existence gate must come up with the same snapshot the entries
        seeded from."""
        ok = self.lww.seed(key, *payload)
        if ok and key[2].endswith(DIR_SUFFIX) and key not in self._dir_paths:
            self._dir_paths[key] = set(
                probe.dir_paths.get((key[1], key[2]), {"/"}))
        return ok

    def _rebuild_merge(self) -> None:
        """Crash-restart: rebuild the device merge lanes by replaying each
        known document's sequenced deltas through the kernel in bulk — the
        server-side device catch-up path (reference deltaManager.ts:1380
        fetchMissingDeltas, applied at partition scale). Channels with a
        stored summary seed from it first, then replay only the tail past
        the summary's sequence number."""
        if self.deltas is None or not self.materialize or not self.docs:
            return
        from .lambdas.scriptorium import query_deltas
        next_seq = np.asarray(self.tstate.next_seq)
        streams: Dict[tuple, List[HostOp]] = {}
        lww_streams: Dict[tuple, List[tuple]] = {}
        for doc_id, dl in self.docs.items():
            probe = self._probe_summary(doc_id)
            seeded_before: Dict[tuple, int] = {}
            if probe is not None:
                for (store, channel), payload in probe.channels.items():
                    key = (doc_id, store, channel)
                    if self.merge.seed(key, *payload):
                        # The seeded base already reflects ops <= the
                        # summary seq for THIS channel; unseeded channels
                        # still replay from zero.
                        seeded_before[key] = probe.sequence_number
                for (store, channel), payload in \
                        probe.lww_channels.items():
                    key = (doc_id, store, channel)
                    if self._seed_lww(key, payload, probe):
                        seeded_before[key] = probe.sequence_number
            # Bound at the restored checkpoint's last seq: deltas persisted
            # by a flush that crashed before checkpointing will be
            # re-sequenced by the raw-log replay (same seqs, scriptorium
            # dedups) and applied to the merge lanes THEN — replaying them
            # here too would double-apply.
            last_seq = int(next_seq[dl.lane]) - 1
            for row in query_deltas(self.deltas, doc_id, 0, last_seq):
                if row.get("type") != MessageType.OPERATION or \
                        not row.get("client_id"):
                    continue
                p = _Pending(tk.MsgKind.OP, dl.intern(row["client_id"]),
                             row["client_sequence_number"],
                             row["reference_sequence_number"],
                             DocumentMessage(
                                 client_sequence_number=row[
                                     "client_sequence_number"],
                                 reference_sequence_number=row[
                                     "reference_sequence_number"],
                                 type=row["type"],
                                 contents=row.get("contents")),
                             row["client_id"])
                self._collect_channel_op(streams, lww_streams, doc_id, p,
                                         row["sequence_number"],
                                         row["minimum_sequence_number"],
                                         seeded_before=seeded_before)
        if streams:
            self.merge.apply(streams)
        if lww_streams:
            self.lww.apply(lww_streams)

    def _checkpoint(self) -> None:
        if self._pending_offset is None:
            return
        if self.checkpoints is not None:
            t = jax.tree_util.tree_map(
                lambda x: np.asarray(x).tolist(), self.tstate)
            self.checkpoints.upsert(
                lambda d: d.get("kind") == "tpu-sequencer",
                {"kind": "tpu-sequencer", "state": {
                    "docs": {doc: dl.dump() for doc, dl in self.docs.items()},
                    "tstate": t._asdict(),
                }})
        self.context.checkpoint(self._pending_offset)
        self._pending_offset = None

    # -- ingestion ---------------------------------------------------------
    def handler(self, message: QueuedMessage) -> None:
        if isinstance(message.value, (bytes, bytearray)):
            # Wire-serialized boxcar off the raw log: the native-pump path.
            return self.handler_raw(message)
        boxcar: Boxcar = message.value
        doc_id = boxcar.document_id
        dl = self._doc(doc_id)
        if message.offset <= dl.log_offset:
            return  # checkpointed replay (deli/lambda.ts:143)
        queue = self.pending.setdefault(doc_id, [])
        for msg in boxcar.contents:
            queue.append(self._parse(dl, boxcar.client_id, msg))
        # Slow-path parse may have interned new clients: flag the doc so
        # the next fast flush re-syncs ONLY it into the pump.
        if doc_id in self._pump_known and \
                dl.next_ordinal > self._pump_synced.get(doc_id, 0):
            self._pump_sync_dirty.add(doc_id)
        dl.log_offset = message.offset
        self._pending_offset = message.offset

    def _place(self, tree):
        """Shard a batched pytree's leading (lane) axis over 'dp'; no-op
        without a mesh."""
        if self.mesh is None:
            return tree
        from ..parallel.mesh import shard_docs
        return shard_docs(self.mesh, tree)

    def _place_cols(self, arr: np.ndarray, lane_axis: int = 1):
        """H2D a staging array with its lane axis sharded over 'dp'."""
        x = jnp.asarray(arr)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            spec = [None] * x.ndim
            spec[lane_axis] = "dp"
            x = jax.device_put(
                x, NamedSharding(self.mesh, PartitionSpec(*spec)))
        return x

    def handler_raw(self, message: QueuedMessage) -> None:
        """Raw-log ingest: message.value is a serialized wire boxcar
        (server/wire.py boxcar_to_wire), message.key the document id.
        Bytes are staged as-is; the native pump parses them at flush
        time, so per-message host cost here is a dict probe and a list
        append — the reference's thin socket->kafka producer hop
        (alfred/index.ts:305)."""
        if self._pump is None:
            from .wire import boxcar_from_wire
            try:
                value = boxcar_from_wire(message.value)
            except Exception as err:  # noqa: BLE001 — untrusted bytes
                # Same poison containment as the pump path: an
                # undecodable record can never become valid on
                # redelivery — drop it, keep the lambda alive.
                self.poison_frames += 1
                import logging
                logging.getLogger(__name__).warning(
                    "dropping undecodable raw frame for %r at "
                    "offset %s: %s", message.key, message.offset, err)
                return
            self.handler(QueuedMessage(
                topic=message.topic, partition=message.partition,
                offset=message.offset, key=message.key,
                value=value))
            return
        doc_id = message.key
        last = self._raw_offsets.get(doc_id)
        if last is None:
            dl = self.docs.get(doc_id)
            last = dl.log_offset if dl is not None else -1
        if message.offset <= last:
            return  # checkpointed replay (deli/lambda.ts:143)
        if doc_id not in self._pump_known:
            self._register_pump_doc(doc_id)
        # fluidlint: disable=UNBOUNDED_QUEUE — bounded at the front
        # door: this backlog rides occupancy_hints staged_ops into the
        # admission controller's queue depth, which sheds ingest before
        # it can outgrow admission.queueLimit (docs/overload.md); a
        # broker consumer cannot reject mid-partition without wedging
        # the offset cursor.
        self._raw_backlog.append((message.offset, doc_id, message.value))
        self._raw_offsets[doc_id] = message.offset
        self._pending_offset = message.offset

    def _register_pump_doc(self, doc_id: str) -> None:
        """Sync an existing (or brand-new) document into the pump's
        intern tables so pump client ordinals continue any numbering the
        object path or a checkpoint restore already assigned."""
        ord_ = self._pump.preload_doc(doc_id)
        while len(self._pump_docs) <= ord_:
            self._pump_docs.append(None)
        self._pump_docs[ord_] = doc_id
        self._pump_ord[doc_id] = ord_
        self._pump_known.add(doc_id)
        dl = self._doc(doc_id)
        if ord_ >= len(self._pump_lane):
            grown = np.full(max(len(self._pump_lane) * 2, ord_ + 1), -1,
                            np.int32)
            grown[:len(self._pump_lane)] = self._pump_lane
            self._pump_lane = grown
        self._pump_lane[ord_] = dl.lane
        for cid, o in dl.interner.items():
            self._pump.preload_client(ord_, cid, o)
        self._pump_synced[doc_id] = dl.next_ordinal

    def _doc(self, doc_id: str) -> _DocLane:
        dl = self.docs.get(doc_id)
        if dl is None:
            lane = len(self.docs)
            if lane >= self.lanes:
                self._grow_lanes()
            dl = _DocLane(lane)
            self.docs[doc_id] = dl
        return dl

    def _grow_lanes(self) -> None:
        old = self.lanes
        grown = tk.make_ticket_state(self.k, batch=old * 2)
        self.tstate = self._place(jax.tree_util.tree_map(
            lambda g, s: g.at[:old].set(s), grown, self.tstate))
        self.lanes = old * 2

    def _grow_clients(self) -> None:
        k2 = self.k * 2
        t = self.tstate

        def widen(col, fill):
            out = jnp.full((self.lanes, k2), fill, col.dtype)
            return out.at[:, :self.k].set(col)

        self.tstate = self._place(t._replace(
            client_ids=widen(t.client_ids, -1),
            client_ref=widen(t.client_ref, tk.INT32_MAX),
            client_cseq=widen(t.client_cseq, 0),
        ))
        self.k = k2

    def _parse(self, dl: _DocLane, client_id: Optional[str],
               msg: DocumentMessage) -> _Pending:
        if msg.type == MessageType.CLIENT_JOIN:
            detail = _detail(msg)
            joining = detail.get("clientId", client_id)
            dl.last_seen[joining] = time.time()
            return _Pending(tk.MsgKind.JOIN, dl.intern(joining), 0, 0, msg,
                            None)
        if msg.type == MessageType.CLIENT_LEAVE:
            detail = _detail(msg)
            leaving = detail if isinstance(detail, str) else \
                detail.get("clientId", client_id)
            dl.last_seen.pop(leaving, None)
            dl.evicting.discard(leaving)
            return _Pending(tk.MsgKind.LEAVE, dl.intern(leaving), 0, 0, msg,
                            None)
        if client_id is None:
            return _Pending(tk.MsgKind.SYSTEM, -1, 0, 0, msg, None)
        dl.last_seen[client_id] = time.time()
        return _Pending(tk.MsgKind.OP, dl.intern(client_id),
                        msg.client_sequence_number,
                        msg.reference_sequence_number, msg, client_id)

    # -- the device flush --------------------------------------------------
    def flush(self) -> None:
        """One serving flush. Traced as the ``serving.flush`` parent span
        (continuing the first traced op's context when one is pending)
        with the named sub-spans — pack, dispatch, readback, fold/rescue,
        payload GC — recorded by the stages below; each stage also feeds
        its ``serving.*`` latency histogram unconditionally, so the
        flush-p99/p50 spread attributes to a stage even with tracing
        off (server/monitor.py `/metrics.prom` + SLO)."""
        if self.stall_hook is not None:
            self.stall_hook()
        with tracing.span("serving.flush", parent=self._flush_parent(),
                          root=True, hist="serving.flush") as _fsp:
            # Device-measured sub-facts enrich the flush span: the
            # deltas of the device.* telemetry counters across this
            # flush (windows retired during it — including deferred
            # windows from earlier flushes draining now — attribute
            # here, mirroring the deferred-readback convention).
            tok = device_stats.begin_flush() \
                if device_stats.enabled() else None
            self._flush_traced()
            if tok is not None:
                facts = device_stats.flush_facts(tok)
                if facts:
                    _fsp.set(**facts)

    def occupancy_hints(self) -> dict:
        """Live occupancy for the admission controller (server/
        admission.py): staged-but-unflushed ops (raw fast-path backlog +
        slow-path pending queues) and the in-flight WINDOW fill. Host-
        state reads only — never blocks on the device.

        Window-counted, not entry-counted: a K-window fused burst is one
        ring entry but K windows of committed in-flight work — reporting
        it as fill 1 would let the controller's latency term read a long
        scan step as calm (ring "mostly empty" zeroes the term). Staged
        (packed, not yet dispatched) burst windows count too; the
        controller clamps the resulting fill fraction at 1.0 so bursting
        by design never throttles on its own."""
        return {
            "staged_ops": len(self._raw_backlog)
            + sum(len(q) for q in self.pending.values()),
            "ring_occupancy": self._in_flight_windows(),
            "ring_depth": self.ring_depth,
        }

    def _in_flight_windows(self) -> int:
        """Dispatched-but-unread windows (burst entries count their K)
        plus staged-but-undispatched windows."""
        return sum(e.get("n_windows", 1) for e in self._ring) \
            + len(self._staged)

    def _flush_parent(self):
        """The first pending traced op's context, if any (slow/object
        path only: fast-path backlogs are raw bytes, parsed later)."""
        if not tracing.enabled():
            return None
        for q in self.pending.values():
            for p in q:
                ctx = tracing.message_context(p.msg)
                if ctx is not None:
                    return ctx
        return None

    def _flush_traced(self) -> None:
        fast_active: List[str] = []
        if self._raw_backlog:
            fast_active = self._flush_raw()
        else:
            self.drain()
        # Eviction checks only documents with activity in THIS flush —
        # the scalar deli's per-boxcar scope; a completely quiet document
        # never evicts (its idle writer had no remote ops to heartbeat
        # against either).
        self._evict_ghosts(sorted(
            {d for d, q in self.pending.items() if q} | set(fast_active)))
        if any(self.pending.values()):
            # Slow windows touch the same merge/LWW lanes a deferred fast
            # window's recovery might roll back — settle it first.
            self.drain()
        # Each window consumes at least one pending message per live doc,
        # so this loop is bounded by the backlog length.
        while any(self.pending.values()):
            self._flush_window()
        # Slow-path traffic only ever ticks the compaction cadence INSIDE
        # apply() (where the collection must defer); this is its safe
        # boundary — every window above has fully applied. In-flight ring
        # windows are the same hazard class: their recovery replays
        # op_ids and pre-window rows numbered against the CURRENT table,
        # so no renumbering while any are in flight — and STAGED burst
        # windows more so (their packed cols embed op ids and lane
        # placements that a renumber/compaction would invalidate before
        # they even dispatch).
        if not self._ring and not self._staged:
            if self._gc_due:
                self._run_fast_gc()
            with tracing.span("serving.gc", hist="serving.gc"):
                self.merge.maybe_compact_payload_ids()
            self._checkpoint()
        else:
            # Emit-bearing window drains checkpoint their own offsets.
            # Lane compactions that came due mid-ring must not starve
            # under sustained traffic: once 2x overdue, pay one full
            # drain and run them at the now-safe boundary.
            if self._gc_due and (
                    self.merge.flushes_since_compact
                    >= 2 * self.merge.compact_every
                    or self.lww.windows_since_value_compact
                    >= 2 * self.lww.value_compact_every):
                self.drain()
                self._run_fast_gc()
            gauge("serving.ring_occupancy",
                  float(self._in_flight_windows()))

    def _run_fast_gc(self) -> None:
        """The fast path's due lane compactions, at a ring-empty boundary
        (compact_all/_fold_crowded move lanes; in-flight windows staged
        against the old placement would corrupt their successors)."""
        assert not self._ring and not self._staged
        self._gc_due = False
        # compact_all's _fold_crowded reseeds channels at new (bucket,
        # lane) placements: any flush staging resolved before this point
        # is stale — bump the gen so the window loop re-resolves, exactly
        # as it does after a fold/rescue.
        self._recovery_gen += 1
        with tracing.span("serving.gc", hist="serving.gc"):
            if self.merge.flushes_since_compact >= self.merge.compact_every:
                self.merge.compact_all()
            if self.lww.windows_since_value_compact >= \
                    self.lww.value_compact_every:
                self.lww.compact_values()

    # -- the fast (native-pump) flush --------------------------------------
    def _flush_raw(self) -> List[str]:
        """Flush the raw-bytes backlog through the native pump + fused
        device windows. Documents with shapes the pump cannot model
        (leaves, group ops, items payloads, malformed frames) — or with
        older object-path messages still pending — route their WHOLE
        backlog through the object slow path this flush, preserving
        per-document ordering and exact slow-path semantics."""
        from . import pump as P
        from .wire import boxcar_from_wire

        if self.merge.paged:
            # R10 one-in-flight: the previous flush's megakernel ring
            # drains before ANY of this flush's work — the slow-path
            # fallback routing below and the staging's flush_lane_for
            # both read the host page scalars that the drain adopts.
            # Lane GC that came due mid-ring runs at this now-empty
            # boundary (the _flush_traced boundary only fires when the
            # ring is ALREADY empty, which a one-in-flight tail ride
            # would otherwise starve).
            self.drain()
            if self._gc_due:
                self._run_fast_gc()

        backlog = self._raw_backlog
        self._raw_backlog = []
        bufs = [b for _, _, b in backlog]
        # Re-sync pump client interners for docs the SLOW path interned
        # into since the last flush (fallback joins, eviction, restore
        # replay): the pump must never hand out an ordinal the host side
        # already assigned to a different client. Only the dirty set —
        # the full _pump_known sweep was O(docs) per flush even when no
        # slow-path intern had happened.
        if self._pump_sync_dirty:
            for doc_id in self._pump_sync_dirty & self._pump_known:
                dl = self.docs.get(doc_id)
                if dl is None:
                    continue
                synced = self._pump_synced.get(doc_id, 0)
                if dl.next_ordinal > synced:
                    ord_ = self._pump_ord[doc_id]
                    for cid, o in dl.interner.items():
                        if o >= synced:
                            self._pump.preload_client(ord_, cid, o)
                    self._pump_synced[doc_id] = dl.next_ordinal
            self._pump_sync_dirty.clear()
        # The native parse overlaps the in-flight ring windows' device
        # execution and result transfers (pipelined mode); everything
        # lane-state-dependent drains first below.
        with tracing.span("serving.pack", hist="serving.pack",
                          stage="parse"):
            parsed = self._pump.parse(bufs)
            cols = parsed.cols
            self._mirror_pump_interns(parsed)

        # --- fallback routing (doc granularity) ---------------------------
        flags = cols[P.FLAGS]
        doc_col = cols[P.DOC]
        fb_rows = (flags & P.F_FALLBACK) != 0
        slow_ids: set = set()
        for o in np.unique(doc_col[fb_rows]).tolist():
            if o >= 0:
                slow_ids.add(self._pump_docs[o])
        for row in np.flatnonzero(fb_rows & (doc_col < 0)).tolist():
            slow_ids.add(backlog[int(cols[P.BUF, row])][1])
        # Docs with object-path messages still queued must stay ordered.
        slow_ids |= {d for d, q in self.pending.items() if q}

        doc_active: Dict[str, int] = {}
        for off, doc_id, _ in backlog:
            doc_active[doc_id] = max(doc_active.get(doc_id, -1), off)
        for off, doc_id, buf in backlog:
            if doc_id in slow_ids:
                try:
                    value = boxcar_from_wire(buf)
                except Exception as err:  # noqa: BLE001 — untrusted bytes
                    # Deterministic poison: an undecodable log record can
                    # never become valid on redelivery — drop THIS frame
                    # (logged), keep every innocent frame flowing
                    # (reference kafka-service catches extractBoxcar
                    # failures the same way).
                    self.poison_frames += 1
                    import logging
                    logging.getLogger(__name__).warning(
                        "dropping undecodable raw frame for %r at "
                        "offset %s: %s", doc_id, off, err)
                    continue
                self.handler(QueuedMessage(
                    topic="rawdeltas", partition=0, offset=off, key=doc_id,
                    value=value))
        for doc_id, off in doc_active.items():
            if doc_id not in slow_ids:
                self.docs[doc_id].log_offset = max(
                    self.docs[doc_id].log_offset, off)

        # --- fast row selection -------------------------------------------
        n = parsed.n
        fast = ~fb_rows & (cols[P.KIND] != tk.MsgKind.NOOP)
        if slow_ids:
            slow_ords = np.array(
                [o for o, name in enumerate(self._pump_docs)
                 if name in slow_ids], np.int32)
            fast &= ~np.isin(doc_col, slow_ords)
        rows = np.flatnonzero(fast)
        now = time.time()
        if rows.size == 0:
            return sorted(doc_active.keys() - slow_ids)

        # last-seen stamps for eviction (unique (doc, client) pairs).
        dc = (doc_col[rows].astype(np.int64) << 32) | \
            (cols[P.CLIENT, rows].astype(np.int64) & 0xFFFFFFFF)
        for pair in np.unique(dc[cols[P.CLIENT, rows] >= 0]).tolist():
            dl = self.docs[self._pump_docs[pair >> 32]]
            cid = dl.ordinals.get(pair & 0xFFFFFFFF)
            if cid is not None:
                dl.last_seen[cid] = now

        # Pre-size the client table (invariant: overflow on device means a
        # sizing bug, exactly as in the slow path).
        need_k = max((dl.next_ordinal for dl in self.docs.values()),
                     default=0)
        while self.k < need_k:
            self._grow_clients()

        # --- window assignment --------------------------------------------
        lanes_r = self._pump_lane[doc_col[rows]]
        pos = _cumcount(lanes_r)
        max_per_doc = int(pos.max()) + 1
        # Pipelining: clean fast windows defer their result fetch + emit
        # into the in-flight ring (the NEXT flush's native parse then
        # overlaps their transfers). Flushes with slow-routed docs or
        # pending object-path messages stay synchronous — their later
        # work touches the same lane state a deferred recovery might
        # roll back — and drain the ring BEFORE resolving lanes, so a
        # drain-time fold/promotion cannot stale this flush's staging.
        defer_ok = (self.pipelined and not slow_ids
                    and not any(self.pending.values()))
        if not defer_ok:
            self.drain()
        per_doc = np.bincount(lanes_r)
        T, depth = self._adaptive_shape(max_per_doc,
                                        per_doc[per_doc > 0])
        win = (pos // T).astype(np.int64)
        slot = (pos % T).astype(np.int64)
        n_windows = int(win.max()) + 1

        # Payload blocks for the whole flush (op ids + value ids).
        with tracing.span("serving.pack", hist="serving.pack",
                          stage="payload-blocks"):
            merge_all = np.flatnonzero(
                fast & (cols[P.FAMILY] == P.FAM_MERGE))
            mbase, chan_ok, chan_b, chan_l = self._merge_block_and_lanes(
                parsed, merge_all)
            lww_all = np.flatnonzero(fast & (cols[P.FAMILY] == P.FAM_LWW))
            vbase, lchan_ok, lchan_b, lchan_l = self._lww_block_and_lanes(
                parsed, lww_all)

        row_seq = np.zeros(rows.size, np.int32)
        row_msn = np.zeros(rows.size, np.int32)
        # Per-window risk (host occupancy hints): a window whose staged
        # lanes might overflow dispatches SYNCHRONOUSLY behind a full
        # drain — its fold/rescue then runs with nothing in flight (the
        # cheap sync recovery), and the expensive mid-ring quarantine
        # fixup stays reserved for genuinely unpredicted overflow
        # (overlap-slot / anno-ring exhaustion).
        win_m = win[np.searchsorted(rows, merge_all)] \
            if merge_all.size else np.zeros(0, np.int64)
        win_l = win[np.searchsorted(rows, lww_all)] \
            if lww_all.size else np.zeros(0, np.int64)
        risky, donate_ok = self._assess_windows(
            parsed, n_windows, merge_all, win_m, chan_ok, chan_b, chan_l,
            win_l, lchan_ok, lchan_b, lchan_l)
        gen_seen = self._recovery_gen
        if self.merge.paged:
            # --- paged fast flush (R10) -----------------------------------
            # Every window stages a page-group job set into the
            # megakernel ring; the whole flush leaves as ONE
            # serve_megakernel dispatch below (the next flush's
            # top-of-flush drain joins it — depth-1 pipelining). Risky
            # windows (non-insert merge traffic whose overlap/anno rings
            # may exhaust, or LWW fit risk) flush the staged ring and
            # drain immediately so their likely rescue runs with nothing
            # behind it; a rescue moves pages, so the flush re-resolves
            # its group directory before staging more windows (invariant
            # R3, paged form).
            w = 0
            while w < n_windows:
                sel = win == w
                wd = self._stage_fast_window(
                    parsed, rows[sel], lanes_r[sel], slot[sel], T,
                    mbase, chan_ok, chan_b, chan_l,
                    vbase, lchan_ok, lchan_b, lchan_l,
                    row_seq, sel, row_msn,
                    donate=self.merge.pages.donate)
                self._staged.append(wd)
                increment("serving.ring_windows_deferred")
                wd["counted_deferred"] = True
                if risky[w] or not defer_ok:
                    self._dispatch_staged_megakernel()
                    self.drain()
                    if self._recovery_gen != gen_seen:
                        gen_seen = self._recovery_gen
                        chan_ok, chan_b, chan_l = \
                            self._resolve_merge_lanes(
                                cols[P.CHAN, merge_all])
                        lchan_ok, lchan_b, lchan_l = \
                            self._resolve_lww_lanes(cols[P.CHAN, lww_all])
                        risky, donate_ok = self._assess_windows(
                            parsed, n_windows, merge_all, win_m, chan_ok,
                            chan_b, chan_l, win_l, lchan_ok, lchan_b,
                            lchan_l, start_w=w + 1)
                w += 1
            n_windows = 0  # staging done; the bucketed loop must not run
        burst_on = (defer_ok and self.fused_bursts
                    and self.donate_lane_states)
        w = 0
        while w < n_windows:
            burst_w = (burst_on and not risky[w]
                       and bool(donate_ok[w]))
            defer_w = defer_ok and (not risky[w]
                                    or self.defer_risky_windows)
            if burst_w or defer_w:
                if not burst_w and self._staged:
                    # A non-burstable window interrupts accumulation:
                    # dispatch the staged run first (FIFO — emits and
                    # lane mutations must land in stage order).
                    increment("serving.burst_breaks")
                    self._dispatch_staged_burst()
                # Bounded ring admission: retire the oldest entry once
                # the ring is full (for burst windows _drain_one first
                # flushes the staged run as a scan, keeping FIFO).
                while len(self._ring) >= depth:
                    self._drain_one()
                if self._ring_fixup or self._ring_fixup_lww:
                    self.drain()
            elif self._staged or self._ring:
                # Sync dispatch (risky or unpipelined): settle every
                # in-flight window first — _finish_window's quarantine
                # direction assumes ring entries are LATER windows.
                # drain() dispatches any staged burst before joining.
                if self._staged:
                    increment("serving.burst_breaks")
                self.drain()
            if self._recovery_gen != gen_seen:
                # A fold/rescue (drained window's, or the previous sync
                # window's own) may have moved channels: re-resolve this
                # flush's lane placement and re-assess the remaining
                # windows (docs/serving_pipeline.md invariant R3).
                gen_seen = self._recovery_gen
                chan_ok, chan_b, chan_l = self._resolve_merge_lanes(
                    cols[P.CHAN, merge_all])
                lchan_ok, lchan_b, lchan_l = self._resolve_lww_lanes(
                    cols[P.CHAN, lww_all])
                risky, donate_ok = self._assess_windows(
                    parsed, n_windows, merge_all, win_m, chan_ok, chan_b,
                    chan_l, win_l, lchan_ok, lchan_b, lchan_l, start_w=w)
                # Re-derive this window's routing against the fresh
                # placement before staging anything.
                continue
            sel = win == w
            wd = self._stage_fast_window(
                parsed, rows[sel], lanes_r[sel], slot[sel], T,
                mbase, chan_ok, chan_b, chan_l,
                vbase, lchan_ok, lchan_b, lchan_l,
                row_seq, sel, row_msn,
                donate=self.donate_lane_states and bool(donate_ok[w]))
            if burst_w:
                self._staged.append(wd)
                increment("serving.ring_windows_deferred")
                # Deferral counted at stage; the dispatch paths below
                # (burst chunk or solo remainder) must not re-count it.
                wd["counted_deferred"] = True
                if len(self._staged) >= self.burst_depth:
                    self._dispatch_staged_burst()
            else:
                self._dispatch_staged_window(wd, defer=defer_w)
            w += 1

        emit_args = (bufs,
                     [self._pump_docs[int(o)] for o in doc_col[rows]],
                     rows, cols, row_seq, row_msn)
        if defer_ok and (self._staged or self._ring):
            # Attached to the flush's LAST window: its drain (after every
            # earlier window filled its row_seq/row_msn slice) emits and
            # checkpoints for the whole flush. Staged windows are always
            # newer than every ring entry (dispatch preserves FIFO).
            if self._staged:
                self._staged[-1]["emit_args"] = emit_args
            else:
                tail = self._ring[-1]
                if "burst" in tail:
                    # The flush's last window already dispatched inside
                    # a burst entry: emits ride that WINDOW's retire so
                    # ordering stays per-window uniform.
                    tail["burst"][-1]["emit_args"] = emit_args
                else:
                    tail["emit_args"] = emit_args
        else:
            self._emit_fast_window(emit_args)
        # Load-adaptive burst sizing: dispatch whatever accumulated the
        # moment the DEVICE goes idle — a single staged window rides
        # plain serve_window (the burst degrades to exactly the
        # per-window ring under light load, keeping the ring's
        # pack/execute overlap), while a device running behind lets
        # staged windows pile up and leave as ONE scan (dispatch count
        # per window shrinks precisely when dispatch pressure is the
        # bottleneck). The burst_depth cap above bounds staging memory
        # and emit latency either way.
        if self._staged and self.merge.paged:
            # R10: the flush's staged windows leave as ONE megakernel
            # dispatch; the NEXT flush's top-of-flush drain joins it.
            self._dispatch_staged_megakernel()
        elif self._staged and not self._device_busy():
            self._dispatch_staged_burst()
        occ = self._in_flight_windows()
        gauge("serving.ring_occupancy", float(occ))
        peak = max(occ, int(counter_get("serving.ring_peak_occupancy")))
        gauge("serving.ring_peak_occupancy", float(peak))
        return sorted(doc_active.keys() - slow_ids)

    def _device_busy(self) -> bool:
        """Is dispatched work still in flight? On async backends (a
        tunneled TPU) fetch threads exit the moment their D2H lands, so
        a live thread means the device or transfer is still working
        through the ring — staged windows should accumulate into a
        bigger scan rather than queue behind it. On blocking-dispatch
        backends (CPU: the jit call runs the program inline, so threads
        die instantly) undrained ring entries are the only in-flight
        signal: results nobody has joined yet mean nobody is waiting,
        so batching costs nothing — dispatches serialize either way."""
        if any(e["thread"].is_alive() for e in self._ring):
            return True
        return self._dispatch_blocking and bool(self._ring)

    def _emit_fast_window(self, emit_args) -> None:
        bufs, doc_ids_r, rows, cols, row_seq, row_msn = emit_args
        ordinals_r = [self.docs[d].ordinals for d in doc_ids_r]
        window = SequencedWindow(bufs, doc_ids_r, ordinals_r, rows, cols,
                                 row_seq, row_msn)
        if self.emit_window is not None:
            self.emit_window(window)
        else:
            for doc_id, msg in window.messages():
                self.emit(doc_id, msg)
        # Compaction cadence bookkeeping (the fast path bypasses
        # MergeLaneStore.apply / LwwLaneStore.apply which normally tick).
        # The compactions themselves ALWAYS defer to the flush-boundary
        # handler (end of flush(), or the starvation drain): this method
        # also runs from a mid-flush _drain_one, where an inline
        # compact_all fold would move lanes the CURRENT flush already
        # resolved staging against — and GC, unlike recovery, does not
        # ride the _recovery_gen staleness re-resolve alone (it bumps
        # the gen too, belt and braces; see _run_fast_gc).
        self.merge.flushes_since_compact += 1
        self.lww.windows_since_value_compact += 1
        due = (self.merge.flushes_since_compact >= self.merge.compact_every
               or self.lww.windows_since_value_compact
               >= self.lww.value_compact_every)
        if due and not self._gc_due:
            self._gc_due = True
            if self._ring:
                increment("serving.ring_gc_deferred")

    def drain(self) -> None:
        """Finish EVERY deferred fast window, oldest first: dispatch any
        staged burst, then join each result transfer, then nacks,
        overflow recovery, the flush's batched emit, and its checkpoint
        — always on the caller's thread, so lane stores are never
        touched concurrently. A completed full drain clears the overflow
        quarantine: every window that could carry a quarantined
        channel's ops has re-applied them."""
        if self._staged:
            self._dispatch_staged()
        while self._ring:
            self._drain_one()
        if self._ring_fixup or self._ring_fixup_lww:
            self._ring_fixup.clear()
            self._ring_fixup_lww.clear()

    def _drain_one(self) -> None:
        """Retire the OLDEST in-flight ring entry (FIFO: emits and lane
        mutations must land in dispatch order). Staged windows dispatch
        FIRST: retiring an entry can run a recovery that moves lanes,
        and staged windows' packed placements must reach the device
        before any move (their results then ride the same quarantine
        fixup every later in-flight window does)."""
        if self._staged:
            self._dispatch_staged()
        ctx = self._ring.popleft()
        increment("serving.ring_drains")
        _t0 = time.perf_counter()
        ctx["thread"].join()
        # The deferred window's D2H: attributed to the flush that
        # DISPATCHED it (ctx["trace_ctx"]), measured as the join stall
        # the draining flush actually pays.
        tracing.record_span("serving.readback", ctx.get("trace_ctx"),
                            _t0, time.perf_counter(),
                            hist="serving.readback", deferred=True)
        if "error" in ctx:
            raise ctx["error"]
        wins = ctx.get("burst")
        if wins is None:
            self._finish_window(ctx)
            self._retire_window(ctx)
            return
        # A burst entry: ONE stacked readback finishes its K windows in
        # stage order; windows with burst siblings still behind them
        # quarantine any recovery exactly as if the siblings were ring
        # entries. Dispatches-per-burst (1 scan + any recovery re-runs
        # its windows' finish triggers) feeds the burst histogram — the
        # figure the fused-smoke grades at <= 2.
        rec0 = counter_get("serving.recovery_dispatches")
        for k, wd in enumerate(wins):
            wd["flat"] = ctx["flat"][k]
            wd["burst_more"] = k + 1 < len(wins)
            self._finish_window(wd)
            self._retire_window(wd)
        dispatches = 1.0 + (counter_get("serving.recovery_dispatches")
                            - rec0)
        increment("serving.burst_dispatch_total", dispatches)
        from ..telemetry.counters import observe as _observe
        _observe("serving.dispatches_per_burst", dispatches)

    def _retire_window(self, ctx) -> None:
        """The emit + checkpoint tail of a finished window (only the
        flush-final window of a multi-window flush carries emit_args)."""
        if "emit_args" not in ctx:
            return  # a non-final window of a multi-window flush
        self._emit_fast_window(ctx["emit_args"])
        # Commit only the offsets this window's FLUSH covered; offsets
        # staged after the deferral belong to a flush that has not
        # sequenced yet and must survive a crash for replay.
        newer = self._pending_offset
        self._pending_offset = ctx["offset"]
        self._checkpoint()
        if newer is not None and (ctx["offset"] is None
                                  or newer > ctx["offset"]):
            self._pending_offset = newer

    def _mirror_pump_interns(self, parsed) -> None:
        for ord_, name in parsed.new_docs:
            # Normally empty (handler_raw preloads by queue key); covers
            # a boxcar whose documentId differs from its queue key.
            while len(self._pump_docs) <= ord_:
                self._pump_docs.append(None)
            self._pump_docs[ord_] = name
            dl = self._doc(name)
            if ord_ >= len(self._pump_lane):
                grown = np.full(max(len(self._pump_lane) * 2, ord_ + 1),
                                -1, np.int32)
                grown[:len(self._pump_lane)] = self._pump_lane
                self._pump_lane = grown
            self._pump_lane[ord_] = dl.lane
            self._pump_ord[name] = ord_
            self._pump_synced[name] = dl.next_ordinal
            self._pump_known.add(name)
        for doc_ord, ord_, cid in parsed.new_clients:
            name = self._pump_docs[doc_ord]
            dl = self.docs[name]
            if cid not in dl.interner:
                dl.interner[cid] = ord_
                dl.ordinals[ord_] = cid
                dl.next_ordinal = max(dl.next_ordinal, ord_ + 1)
            # Pump-assigned ordinals are by definition in sync.
            self._pump_synced[name] = max(
                self._pump_synced.get(name, 0), ord_ + 1)
        for chan_ord, doc_ord, store, chan in parsed.new_channels:
            assert chan_ord == len(self._pump_chan)
            key = (self._pump_docs[doc_ord], store, chan)
            self._pump_chan.append(key)
            self._chan_ord[key] = chan_ord
        for ord_, key in parsed.new_keys:
            kid = self.lww.intern_key(key)
            if ord_ >= len(self._lww_key_map):
                grown = np.full(max(len(self._lww_key_map) * 2, ord_ + 1),
                                -1, np.int32)
                grown[:len(self._lww_key_map)] = self._lww_key_map
                self._lww_key_map = grown
            self._lww_key_map[ord_] = kid

    def _merge_block_and_lanes(self, parsed, merge_rows: np.ndarray):
        """Register the flush's merge payload block and resolve each
        channel's (bucket, lane), seeding new channels from stored
        summaries exactly as the slow path does. Returns (op-id base,
        per-row ok mask, bucket array, lane array) aligned to
        merge_rows."""
        from ..mergetree.host import MergeArenaBlock
        from . import pump as P
        cols = parsed.cols
        self._flush_merge_rows = merge_rows
        if merge_rows.size == 0:
            self._flush_merge_block = MergeArenaBlock(
                kinds=np.zeros(0, np.int8), textoff=np.zeros(0, np.int32),
                textlen=np.zeros(0, np.int32), arena=b"", bufs=[],
                pbuf=np.zeros(0, np.int32), pstart=np.zeros(0, np.int32),
                pend=np.zeros(0, np.int32))
            self._flush_merge_block.seqs = np.zeros(0, np.int32)
            return 0, np.zeros(0, bool), np.zeros(0, np.int32), \
                np.zeros(0, np.int32)
        mk = cols[P.MKIND, merge_rows]
        fl = cols[P.FLAGS, merge_rows]
        kinds = np.full(merge_rows.size, MergeArenaBlock.K_NONE, np.int8)
        kinds[(mk == 1) & ((fl & P.F_MARKER) != 0)] = MergeArenaBlock.K_MARKER
        kinds[(mk == 1) & ((fl & P.F_MARKER) == 0)] = MergeArenaBlock.K_TEXT
        kinds[(mk == 1) & ((fl & P.F_RUN) != 0)] = MergeArenaBlock.K_RUN
        kinds[(mk == 1) & ((fl & P.F_ITEMS) != 0)] = MergeArenaBlock.K_ITEMS
        kinds[mk == 3] = MergeArenaBlock.K_ANNOTATE
        block = MergeArenaBlock(
            kinds=kinds,
            textoff=cols[P.TEXTOFF, merge_rows].copy(),
            textlen=cols[P.TEXTLEN, merge_rows].copy(),
            arena=parsed.arena, bufs=parsed.bufs,
            pbuf=cols[P.BUF, merge_rows].copy(),
            pstart=cols[P.PSTART, merge_rows].copy(),
            pend=cols[P.PEND, merge_rows].copy())
        block.seqs = np.zeros(merge_rows.size, np.int32)
        mbase = self.merge.payloads.add_block(block)
        self._flush_merge_block = block
        self._flush_merge_rows = merge_rows

        chans = cols[P.CHAN, merge_rows]
        ok_rows, b_rows, l_rows = self._resolve_merge_lanes(chans)
        # Block aging bookkeeping: which lanes reference which of this
        # block's op ids. Non-admitted rows (opaque/degraded channels —
        # the host object path is authoritative for them) are freed NOW:
        # nothing will ever resolve them, and leaving the entries in
        # place would pin this flush's raw buffers forever. Vectorized
        # grouping + one batched free — this runs per fast flush on the
        # ingest hot path.
        lane_ids: Dict[tuple, list] = {}
        ok_idx = np.flatnonzero(ok_rows)
        if ok_idx.size:
            ch = chans[ok_idx]
            order = np.argsort(ch, kind="stable")  # keeps arrival order
            sorted_ch = ch[order]
            sorted_ids = (mbase + ok_idx[order]).tolist()
            bounds = np.flatnonzero(np.diff(sorted_ch)) + 1
            starts = [0, *bounds.tolist()]
            ends = [*bounds.tolist(), len(sorted_ids)]
            for s, e in zip(starts, ends):
                lane_ids[self._pump_chan[int(sorted_ch[s])]] = \
                    sorted_ids[s:e]
        bad_idx = np.flatnonzero(~ok_rows)
        if bad_idx.size:
            self.merge.free_payloads((mbase + bad_idx).tolist())
        if lane_ids:
            self.merge.note_block(block, lane_ids)
        return mbase, ok_rows, b_rows, l_rows

    def _resolve_merge_lanes(self, chans: np.ndarray):
        """Resolve each merge row's channel to its CURRENT (bucket, lane),
        seeding new channels from stored summaries exactly as the slow
        path does. Idempotent — re-run after a mid-ring recovery moved
        channels (promotion/fold/page rescue) to refresh a flush's
        staging. Paged stores resolve to per-flush (group, lane)
        coordinates in a directory rebuilt here (R10): the group is the
        channel's pow2 page-count class after pre-growing its pages for
        this flush's op count, so the megakernel's gathered views fit
        by construction."""
        uniq, inv = np.unique(chans, return_inverse=True)
        ok_u = np.zeros(uniq.size, bool)
        b_u = np.zeros(uniq.size, np.int32)
        l_u = np.zeros(uniq.size, np.int32)
        paged = self.merge.paged
        if paged:
            self.merge.begin_flush_groups()
            n_by_u = np.bincount(inv, minlength=uniq.size)
        for j, ch in enumerate(uniq.tolist()):
            key = self._pump_chan[ch]
            if key in self.merge.opaque:
                continue
            if key not in self.merge.where and self.storage is not None:
                probe = self._probe_summary(key[0])
                if probe is not None:
                    payload = probe.channels.get((key[1], key[2]))
                    if payload is not None:
                        self.merge.seed(key, *payload)
                        if key in self.merge.opaque:
                            continue
            if paged:
                bb, ll = self.merge.flush_lane_for(key, int(n_by_u[j]))
            else:
                bb, ll = self.merge.lane_for(key)
            self.merge.mark_dirty(key)
            ok_u[j] = True
            b_u[j] = bb
            l_u[j] = ll
        return ok_u[inv], b_u[inv], l_u[inv]

    def _lww_block_and_lanes(self, parsed, lww_rows: np.ndarray):
        from . import pump as P
        cols = parsed.cols
        self._flush_lww_rows = lww_rows
        if lww_rows.size == 0:
            return 0, np.zeros(0, bool), np.zeros(0, np.int32), \
                np.zeros(0, np.int32)
        vstart = np.where((cols[P.FLAGS, lww_rows] & P.F_VALUE) != 0,
                          cols[P.PSTART, lww_rows], -1)
        block = _LwwValueBlock(parsed.bufs, cols[P.BUF, lww_rows].copy(),
                               vstart, cols[P.PEND, lww_rows].copy())
        vbase = self.lww.add_value_block(block)
        ok, b, lane = self._resolve_lww_lanes(cols[P.CHAN, lww_rows])
        return vbase, ok, b, lane

    def _resolve_lww_lanes(self, chans: np.ndarray):
        """LWW side of _resolve_merge_lanes (same idempotence contract)."""
        uniq, inv = np.unique(chans, return_inverse=True)
        ok_u = np.zeros(uniq.size, bool)
        b_u = np.zeros(uniq.size, np.int32)
        l_u = np.zeros(uniq.size, np.int32)
        for j, ch in enumerate(uniq.tolist()):
            key = self._pump_chan[ch]
            if key in self.lww.opaque:
                continue
            if key not in self.lww.where and self.storage is not None:
                probe = self._probe_summary(key[0])
                if probe is not None:
                    payload = probe.lww_channels.get((key[1], key[2]))
                    if payload is not None:
                        self._seed_lww(key, payload, probe)
                        if key in self.lww.opaque:
                            continue
            bb, ll = self.lww.lane_for(key)
            self.lww.mark_dirty(key)
            ok_u[j] = True
            b_u[j] = bb
            l_u[j] = ll
        return ok_u[inv], b_u[inv], l_u[inv]

    def _adaptive_shape(self, max_per_doc: int,
                        doc_depths: Optional[np.ndarray] = None
                        ) -> Tuple[int, int]:
        """Pick the window op-depth T and the per-flush ring depth from
        the backlog's per-doc depth distribution plus the rolling
        serving.pack/dispatch/readback histograms.

        The op-depth always comes from the FIXED t_buckets grid — the
        adaptive policy changes which bucket is chosen, never the shape
        vocabulary, so serve_window's compile cache stays bounded
        (JitRetraceProbe-checked in tests/test_pipelined_serving.py).

        Policy: T follows the p95 per-doc depth, not the max. A RAGGED
        backlog (one storm doc atop a fleet of keystroke docs) would
        otherwise pad EVERY lane to the deepest doc — [B, T] staging and
        the scan kernel's step count both scale with T — so the bulk of
        the fleet rides one narrow window and only the storm doc spans
        the extra ring slots. A uniform backlog keeps its exact-depth
        single window: splitting below the backlog depth only multiplies
        the per-dispatch cost (a tunneled chip pays an RPC floor per
        dispatch) since the ring already overlaps pack/execute/readback
        ACROSS windows. The rolling histograms steer the ring depth:
        host-bound traffic (keystroke bursts) shortens the ring so
        results emit sooner; device/transfer-bound traffic keeps it deep
        for overlap."""
        max_t = self.t_buckets[-1]
        need = min(max_per_doc, max_t)
        T = _bucket(need, self.t_buckets)
        depth = 1
        if self.pipelined:
            depth = self.ring_depth
            if self.adaptive_window:
                if doc_depths is not None and doc_depths.size \
                        and need > self.t_buckets[0]:
                    p95 = int(np.percentile(doc_depths, 95))
                    p95b = _bucket(max(1, min(p95, max_t)),
                                   self.t_buckets)
                    # Smallest bucket >= the p95 depth whose window
                    # count stays bounded (the storm doc alone spans
                    # the extra windows; everyone else rides one).
                    for cand in self.t_buckets:
                        if cand < p95b or cand >= T:
                            continue
                        if -(-need // cand) <= max(depth, 8):
                            T = cand
                            break

                def p50(name: str) -> float:
                    w = latency_window(name)
                    return nearest_rank(sorted(w), 0.50) if w else 0.0

                host_ms = p50("serving.pack")
                dev_ms = p50("serving.dispatch") + p50("serving.readback")
                if host_ms > 0.0 and dev_ms <= 0.25 * host_ms:
                    # Host-bound keystroke traffic: shallow ring (emit
                    # latency over overlap).
                    depth = min(depth, 2)
        gauge("serving.ring_depth", float(depth))
        gauge("serving.window_t", float(T))
        return T, depth

    def _probe_fused(self) -> None:
        """Lazy first-dispatch probe: can this backend lower the fused
        VMEM apply (and its INSERT_RUN variant)?"""
        if self._fused_serve is not None:
            return
        from ..mergetree.pallas_apply import (fused_available,
                                             fused_runs_available)
        import jax as _jax
        base = (_jax.default_backend() in ("tpu", "axon")
                and fused_available())
        if base and self.pack_runs and not fused_runs_available():
            # The INSERT_RUN Mosaic variant failed to lower on this
            # backend: keep the fused kernel (the round-3 lever) and
            # drop packing rather than forfeit fused for scan+runs.
            self.pack_runs = False
        self._fused_serve = base

    def _stage_fast_window(self, parsed, rows, lanes, slot, T,
                           mbase, chan_ok, chan_b, chan_l,
                           vbase, lchan_ok, lchan_b, lchan_l,
                           row_seq, flush_sel, row_msn,
                           donate: bool = False) -> dict:
        """Pack one fast window into host staging arrays + job records —
        everything the dispatch needs EXCEPT the device call, so a
        window can sit in the staged-burst queue across flushes. The
        in-flight occupancy bound (hint_pending) is charged HERE: later
        flushes' fit proofs must see staged windows' worst-case rows
        whether or not they have dispatched yet."""
        from . import pump as P
        cols = parsed.cols
        B = self.lanes

        with tracing.span("serving.pack", hist="serving.pack",
                          stage="window-staging"):
            ticket_cols = np.zeros((4, B, T), np.int32)
            ticket_cols[1] = -1
            ticket_cols[0, lanes, slot] = cols[P.KIND, rows]
            ticket_cols[1, lanes, slot] = cols[P.CLIENT, rows]
            ticket_cols[2, lanes, slot] = cols[P.CSEQ, rows]
            ticket_cols[3, lanes, slot] = cols[P.REFSEQ, rows]

            merge_jobs = self._build_merge(parsed, rows, lanes, slot,
                                           mbase, chan_ok, chan_b, chan_l)
            lww_jobs = self._build_lww(parsed, rows, lanes, slot,
                                       vbase, lchan_ok, lchan_b, lchan_l)

        # In-flight occupancy bound: each staged merge op adds at most 2
        # rows, each LWW op at most one key slot; confirmed exactly (and
        # removed from pending) when this window's occupancy plane comes
        # back at its drain. Paged merge needs no charge — flush_lane_for
        # pre-grew every member's pages for the whole flush's op count.
        if not self.merge.paged:
            for j in merge_jobs:
                np.add.at(self.merge.buckets[j["bucket"]].hint_pending,
                          j["lanes"], 2)
        for j in lww_jobs:
            np.add.at(self.lww.buckets[j["bucket"]].hint_pending,
                      j["lanes"], 1)

        return {"parsed": parsed, "B": B, "T": T, "rows": rows,
                "lanes": lanes, "slot": slot,
                "idx": np.flatnonzero(flush_sel),
                "ticket_cols": ticket_cols,
                "merge_jobs": merge_jobs, "lww_jobs": lww_jobs,
                "mbase": mbase, "block": self._flush_merge_block,
                "row_seq": row_seq, "row_msn": row_msn,
                "donated": donate,
                # Staged placements go stale the moment a recovery moves
                # lanes; the GC/drain discipline guarantees gen cannot
                # move while a window sits staged (staged bursts always
                # dispatch before any join/recovery), so stage-time gen
                # IS dispatch-time gen.
                "gen": self._recovery_gen,
                # The offsets THIS window covers: drain() must commit
                # exactly these — the live _pending_offset may already
                # include a newer, not-yet-dispatched backlog.
                "offset": self._pending_offset,
                # The flush's trace position, so the deferred readback
                # (joined by a LATER flush's drain) attributes to the
                # window that dispatched it, not the one that drained it.
                "trace_ctx": tracing.current(),
                # Degrade-path restage context (the fused INSERT_RUN
                # variant failing at a production shape re-builds the
                # merge jobs without packing): the originating flush's
                # row universe, valid cross-flush because the arrays are
                # immutable snapshots.
                "rebuild": (rows, lanes, slot, mbase, chan_ok, chan_b,
                            chan_l, self._flush_merge_rows)}

    def _pad_staged_window(self, wd: dict) -> None:
        """Re-shape a staged window's cols to CURRENT table widths: doc
        lanes and bucket lanes may have grown (new docs/channels in a
        later flush) between staging and dispatch. Growth only appends
        lanes, so zero-padding the lane axis (NOOP rows) is exact; T/Tm
        never change after staging."""
        B = self.lanes
        tc = wd["ticket_cols"]
        if tc.shape[1] < B:
            grown = np.zeros((4, B, tc.shape[2]), np.int32)
            grown[1] = -1
            grown[:, :tc.shape[1], :] = tc
            wd["ticket_cols"] = grown
        wd["B"] = B
        for j in wd["merge_jobs"]:
            if self.merge.paged:
                # R10: pad to the flush group's CURRENT pow2 width —
                # later windows of the same flush may have admitted more
                # members into the group.
                width = self.merge.flush_groups[j["bucket"]].lanes
            else:
                width = self.merge.buckets[j["bucket"]].lanes
            c = j["cols"]
            if c is not None and c.shape[1] < width:
                grown = np.zeros((12, width, c.shape[2]), np.int32)
                grown[:, :c.shape[1], :] = c
                j["cols"] = grown
                if j["runs"] is not None:
                    r = j["runs"]
                    rg = np.zeros((4, width) + r.shape[2:],
                                  np.int32)
                    rg[:, :r.shape[1]] = r
                    j["runs"] = rg
            j["lanes_n"] = width
        for j in wd["lww_jobs"]:
            bucket = self.lww.buckets[j["bucket"]]
            c = j["cols"]
            if c is not None and c.shape[1] < bucket.lanes:
                grown = np.zeros((6, bucket.lanes, c.shape[2]), np.int32)
                grown[1] = -1
                grown[2] = -1
                grown[:, :c.shape[1], :] = c
                j["cols"] = grown
            j["lanes_n"] = bucket.lanes

    def _dispatch_staged_window(self, wd: dict, defer: bool) -> None:
        """Dispatch ONE staged window: the fused device program, then
        either an immediate result fetch (_finish_window) or — pipelined
        — a background transfer joined by the next drain()."""
        from . import serve_step
        self._probe_fused()
        self._pad_staged_window(wd)
        donate = wd["donated"]
        merge_jobs, lww_jobs = wd["merge_jobs"], wd["lww_jobs"]
        ticket_cols = wd["ticket_cols"]

        # Buffer donation (decided by _assess_windows' occupancy-hint fit
        # proof): donated windows update lane states in place — no fresh
        # HBM allocation per window; kept windows retain the pre states
        # the fold/rescue rollback scatters back.
        increment("serving.ring_donated_windows" if donate
                  else "serving.ring_kept_windows")
        increment("serving.window_dispatches")

        # Device telemetry plane (telemetry/device_stats.py): static at
        # dispatch, stamped on the window so _finish_window decodes the
        # flat16 tail only when this window actually carried it.
        stats_on = device_stats.enabled()
        wd["stats"] = stats_on

        # ONE fused device program for the whole window (every extra
        # dispatch is a serialized tunnel RPC), then ONE host sync of the
        # narrow int16 result (msn32_dev is fetched only on the rare
        # msn-span overflow).
        def dispatch(fused):
            step = serve_step.serve_window if donate \
                else serve_step.serve_window_keep
            ledger_name = "serve.window" if donate else "serve.window_keep"
            with compile_ledger.track(ledger_name, step):
                return step(
                    self.tstate, self._place_cols(ticket_cols),
                    [self.merge.buckets[j["bucket"]].state
                     for j in merge_jobs],
                    [self._place_cols(j["cols"]) for j in merge_jobs],
                    [self.lww.buckets[j["bucket"]].state
                     for j in lww_jobs],
                    [self._place_cols(j["cols"]) for j in lww_jobs],
                    fused,
                    [None if j["runs"] is None
                     else self._place_cols(j["runs"])
                     for j in merge_jobs],
                    stats_on)

        with tracing.span("serving.dispatch", hist="serving.dispatch"):
            try:
                (self.tstate, new_merge, new_lww, flat_dev,
                 msn32_dev) = dispatch(self._fused_serve)
            except Exception as err:  # noqa: BLE001 — degrade, never crash
                if not self._fused_serve:
                    raise
                # The fused path failed at THIS production shape (the
                # small probe passed — e.g. the runs variant's 24 extra
                # op columns blew the VMEM budget at a large (capacity,
                # T)). Failures happen at lowering, before execution, so
                # the donated buffers are intact. Degrade in probe-policy
                # order: if this window carries runs, drop PACKING (keep
                # the fused kernel for plain buckets) and re-stage; else
                # forfeit fused. Either way, log loudly — a silent
                # degrade would hide both a Mosaic regression and the
                # perf cliff.
                import logging
                increment("sequencer.fused_degrades")
                had_runs = any(j["runs"] is not None for j in merge_jobs)
                if had_runs and self.pack_runs:
                    self.pack_runs = False
                    logging.getLogger(__name__).warning(
                        "fused INSERT_RUN variant failed at a production "
                        "shape; disabling run packing (%r)", err)
                    merge_jobs = self._restage_merge_jobs(wd)
                    wd["merge_jobs"] = merge_jobs
                    try:
                        (self.tstate, new_merge, new_lww, flat_dev,
                         msn32_dev) = dispatch(self._fused_serve)
                    except Exception as err2:  # noqa: BLE001
                        increment("sequencer.fused_degrades")
                        self._fused_serve = False
                        logging.getLogger(__name__).warning(
                            "fused serving failed without runs too; scan "
                            "path from now on (%r)", err2)
                        (self.tstate, new_merge, new_lww, flat_dev,
                         msn32_dev) = dispatch(False)
                else:
                    self._fused_serve = False
                    logging.getLogger(__name__).warning(
                        "fused serving apply failed; scan path from now "
                        "on (%r)", err)
                    (self.tstate, new_merge, new_lww, flat_dev,
                     msn32_dev) = dispatch(False)
        for j, post in zip(merge_jobs, new_merge):
            self.merge.buckets[j["bucket"]].state = post
            if donate:
                # The donated pre-state buffers were consumed in place;
                # drop the stale reference so a recovery bug trips the
                # explicit pre-is-None degrade, not a deleted-buffer read.
                j["pre"] = None
        for j, post in zip(lww_jobs, new_lww):
            self.lww.buckets[j["bucket"]].state = post
            if donate:
                j["pre"] = None

        wd["msn32_dev"] = msn32_dev
        if defer:
            import threading

            def fetch():
                try:
                    wd["flat"] = np.asarray(flat_dev)
                except Exception as err:  # noqa: BLE001 — surface at join
                    wd["error"] = err

            wd["thread"] = threading.Thread(target=fetch, daemon=True)
            wd["thread"].start()
            self._ring.append(wd)
            if not wd.pop("counted_deferred", False):
                increment("serving.ring_windows_deferred")
        else:
            with tracing.span("serving.readback",
                              hist="serving.readback"):
                wd["flat"] = np.asarray(flat_dev)  # the window's ONE sync
            self._finish_window(wd)

    def _restage_merge_jobs(self, wd: dict) -> List[dict]:
        """Rebuild a staged window's merge jobs (degrade path: packing
        just turned off) against its ORIGINATING flush's row universe,
        preserving the hint_pending charge (same rows, same lanes, same
        +2-per-op bound — no re-add)."""
        (rows, lanes, slot, mbase, chan_ok, chan_b, chan_l,
         flush_rows) = wd["rebuild"]
        return self._build_merge(wd["parsed"], rows, lanes, slot, mbase,
                                 chan_ok, chan_b, chan_l,
                                 flush_rows=flush_rows)

    def _dispatch_staged_burst(self) -> None:
        """Dispatch EVERY staged window, oldest first, as fused scan
        bursts: consecutive staged windows sharing a ticket depth T
        chunk into scan lengths from the fixed grid (compile-cache
        bound); a remainder of one dispatches through plain
        serve_window. Always empties the staged queue — callers rely on
        'staged dispatched before any join' to keep recovery
        quarantine's window-ordering invariant."""
        staged, self._staged = self._staged, []
        i = 0
        while i < len(staged):
            # Longest same-T run from i (T is baked into the stacked
            # ticket planes and the flat16 layout).
            run = i + 1
            while (run < len(staged)
                   and staged[run]["T"] == staged[i]["T"]):
                run += 1
            while i < run:
                left = run - i
                k = 1
                for cand in self._burst_k_grid:
                    if cand <= left:
                        k = cand
                if k >= 2:
                    if not self._dispatch_burst_chunk(staged[i:i + k]):
                        # Lowering failed (counted/logged there): fall
                        # back to per-window dispatch for this chunk.
                        for wd in staged[i:i + k]:
                            self._dispatch_staged_window(wd, defer=True)
                else:
                    self._dispatch_staged_window(staged[i], defer=True)
                i += k

    def _dispatch_burst_chunk(self, wins: List[dict]) -> bool:
        """ONE scanned device program for K staged windows: stack every
        window's packed op planes (NOOP-padded to the union of staged
        buckets), dispatch serve_burst with the donated lane-bucket
        carry, and enter the ring as a single entry whose drain finishes
        all K windows off the stacked narrow result. Returns False if
        the burst program failed to lower (donated buffers intact — the
        caller falls back to per-window dispatch)."""
        from . import serve_step
        self._probe_fused()
        K = len(wins)
        for wd in wins:
            self._pad_staged_window(wd)
        B, T = self.lanes, wins[0]["T"]

        with tracing.span("serving.pack", hist="serving.pack",
                          stage="burst-stack"):
            # Every member passed _pad_staged_window, so each
            # ticket_cols is exactly [4, B, T] and fills its full slice.
            tx = np.empty((K, 4, B, T), np.int32)
            for k, wd in enumerate(wins):
                tx[k] = wd["ticket_cols"]

            def stack_jobs(job_lists, buckets, ncols, fills):
                """Union-bucket stacking: per bucket one [K, ncols,
                lanes, Tm] plane (+ runs for merge); windows without the
                bucket ride all-NOOP padding, and every window's job
                list is rewritten union-aligned so _finish_window parses
                the shared flat16 layout."""
                ids = sorted({j["bucket"] for jl in job_lists for j in jl})
                xs, rxs, states = [], [], []
                aligned: List[List[dict]] = [[] for _ in wins]
                for b in ids:
                    bucket = buckets[b]
                    jobs = [next((j for j in jl if j["bucket"] == b),
                                 None) for jl in job_lists]
                    tm = max(j["cols"].shape[2] for j in jobs
                             if j is not None)
                    arr = np.zeros((K, ncols, bucket.lanes, tm), np.int32)
                    for plane, fill in fills:
                        arr[:, plane] = fill
                    has_runs = any(j is not None and j.get("runs")
                                   is not None for j in jobs)
                    rarr = None
                    if has_runs:
                        from ..mergetree.oppack import RUN_K
                        rarr = np.zeros((K, 4, bucket.lanes, tm, RUN_K),
                                        np.int32)
                    for k, j in enumerate(jobs):
                        if j is None:
                            aligned[k].append(self._empty_job(
                                b, bucket.lanes))
                            continue
                        c = j["cols"]
                        arr[k, :, :c.shape[1], :c.shape[2]] = c
                        if rarr is not None and j.get("runs") is not None:
                            r = j["runs"]
                            rarr[k, :, :r.shape[1], :r.shape[2], :] = r
                        aligned[k].append(j)
                    xs.append(self._place_cols(arr, lane_axis=2))
                    rxs.append(None if rarr is None else
                               self._place_cols(rarr, lane_axis=2))
                    states.append(bucket.state)
                return ids, xs, rxs, states, aligned

            m_ids, merge_xs, runs_xs, merge_states, m_aligned = stack_jobs(
                [wd["merge_jobs"] for wd in wins], self.merge.buckets,
                12, ())
            l_ids, lww_xs, _, lww_states, l_aligned = stack_jobs(
                [wd["lww_jobs"] for wd in wins], self.lww.buckets,
                6, ((1, -1), (2, -1)))

        stats_on = device_stats.enabled()
        with tracing.span("serving.dispatch", hist="serving.dispatch"):
            try:
                with compile_ledger.track("serve.burst",
                                          serve_step.serve_burst):
                    (self.tstate, new_merge, new_lww, flats_dev,
                     msns_dev) = serve_step.serve_burst(
                        self.tstate, tuple(merge_states),
                        tuple(lww_states),
                        self._place_cols(tx, lane_axis=2),
                        tuple(merge_xs), tuple(lww_xs), tuple(runs_xs),
                        self._fused_serve, stats_on)
            except Exception as err:  # noqa: BLE001 — degrade, never crash
                # Lowering failures leave the donated buffers intact
                # (same contract as the per-window degrade ladder); the
                # per-window fallback then runs its own fused degrade.
                # Job lists are still the windows' OWN (union alignment
                # is adopted only below, on success), so serve_window
                # re-dispatches them unchanged. A POST-lowering failure
                # (device OOM mid-scan) may have consumed the donated
                # carry, though — falling back onto deleted/corrupt lane
                # buffers would materialize garbage, so probe for it and
                # re-raise: that failure mode has no safe recovery.
                def _gone(tree):
                    leaf = jax.tree_util.tree_leaves(tree)
                    return bool(leaf) and bool(
                        getattr(leaf[0], "is_deleted", bool)())
                if (_gone(self.tstate) or any(map(_gone, merge_states))
                        or any(map(_gone, lww_states))):
                    raise
                import logging
                increment("serving.burst_fallbacks")
                logging.getLogger(__name__).warning(
                    "fused burst scan failed at K=%d; dispatching the "
                    "chunk per-window (%r)", K, err)
                return False
        for k, wd in enumerate(wins):
            # Union-aligned job lists: each window's _finish_window
            # parses the SHARED flat16 layout (one plane set per union
            # bucket), so its jobs must cover every union bucket in
            # order — placeholders for buckets it never staged.
            wd["merge_jobs"] = m_aligned[k]
            wd["lww_jobs"] = l_aligned[k]
            wd["stats"] = stats_on
            # The scan body runs with noop_skip: the host mirror of the
            # device skip counter needs to know (solo windows never
            # count skips).
            wd["noop_skip"] = True
        for b, post in zip(m_ids, new_merge):
            self.merge.buckets[b].state = post
        for b, post in zip(l_ids, new_lww):
            self.lww.buckets[b].state = post
        for k, wd in enumerate(wins):
            for j in wd["merge_jobs"] + wd["lww_jobs"]:
                # The scan carry was donated: no per-window pre states
                # exist (burst admission proved the windows overflow-
                # free; unpredicted overflow takes the donated degrade
                # + quarantine path, exactly as per-window donation).
                j["pre"] = None
            wd["msn32_dev"] = msns_dev[k]
        increment("serving.ring_donated_windows", K)
        increment("serving.bursts")
        increment("serving.burst_windows", K)

        entry = {"burst": wins, "n_windows": K,
                 "trace_ctx": wins[-1]["trace_ctx"]}
        import threading

        def fetch():
            try:
                entry["flat"] = np.asarray(flats_dev)  # [K, flat] D2H
            except Exception as err:  # noqa: BLE001 — surface at join
                entry["error"] = err

        entry["thread"] = threading.Thread(target=fetch, daemon=True)
        entry["thread"].start()
        self._ring.append(entry)
        return True

    @staticmethod
    def _empty_job(bucket: int, lanes_n: int) -> dict:
        """A window's placeholder for a union bucket it never staged:
        zero rows, so _finish_window's hint/recovery walks are no-ops,
        but lanes_n keeps the shared flat16 plane layout parseable."""
        z = np.zeros(0, np.int64)
        return {"bucket": bucket, "pre": None, "cols": None, "runs": None,
                "lanes_n": lanes_n, "chan": z, "rows": z, "lanes": z,
                "op_ids": z, "val_ids": z, "doc_lane": z, "slot": z}

    def _dispatch_staged(self) -> None:
        """Route the staged queue to its storage layout's dispatcher."""
        if self.merge.paged:
            self._dispatch_staged_megakernel()
        else:
            self._dispatch_staged_burst()

    def _mega_fused_mode(self):
        """Op-phase mode for the megakernel scan body: True runs the
        pallas fused apply+extract (TPU/axon, probed), "interpret" runs
        the IDENTICAL pallas program under the interpreter (how CPU
        tier-1 exercises the kernel), False runs the scan op-phase
        inside the megakernel — still one device program per ring."""
        self._probe_fused()
        if self._fused_serve:
            from ..mergetree.pallas_apply import fused_extract_available
            if fused_extract_available():
                return True
        if self.megakernel_interpret:
            return "interpret"
        return False

    def _dispatch_staged_megakernel(self) -> None:
        """Dispatch EVERY staged window, oldest first, as serving
        megakernels (R10): consecutive windows sharing a ticket depth T
        chunk into scan lengths from the fixed burst grid (the jit
        cache sees only grid-quantized signatures, never the raw
        backlog length). Always empties the staged queue — same
        contract as _dispatch_staged_burst."""
        staged, self._staged = self._staged, []
        i = 0
        while i < len(staged):
            run = i + 1
            while (run < len(staged)
                   and staged[run]["T"] == staged[i]["T"]):
                run += 1
            while i < run:
                left = run - i
                k = 1
                for cand in self._burst_k_grid:
                    if cand <= left:
                        k = cand
                self._dispatch_megakernel_chunk(staged[i:i + k])
                i += k

    def _dispatch_megakernel_chunk(self, wins: List[dict]) -> None:
        """ONE persistent device program for K staged paged windows
        (R10): gather every flush group's pages into views, scan the K
        windows' op planes over them (pallas fused apply+extract, its
        interpreted twin, or the scan kernel — _mega_fused_mode),
        scatter the views back, and enter the ring as a single entry
        whose drain finishes all K windows off the stacked narrow
        flat16 result. Group page ids and pre-ring scalars are staged
        HERE, at dispatch time: the ring is one-in-flight, so the host
        scalars are authoritative until this entry drains."""
        from . import serve_step
        K = len(wins)
        for wd in wins:
            self._pad_staged_window(wd)
        B, T = self.lanes, wins[0]["T"]

        with tracing.span("serving.pack", hist="serving.pack",
                          stage="megakernel-stack"):
            tx = np.empty((K, 4, B, T), np.int32)
            for k, wd in enumerate(wins):
                tx[k] = wd["ticket_cols"]

            def stack_group_jobs(job_lists):
                """Union-group stacking — _dispatch_burst_chunk's
                stack_jobs with flush groups as the bucket axis and no
                pre states (the megakernel's readback carries the
                gathered pre views instead)."""
                ids = sorted({j["bucket"] for jl in job_lists
                              for j in jl})
                xs, rxs = [], []
                aligned: List[List[dict]] = [[] for _ in wins]
                for g in ids:
                    width = self.merge.flush_groups[g].lanes
                    jobs = [next((j for j in jl if j["bucket"] == g),
                                 None) for jl in job_lists]
                    tm = max(j["cols"].shape[2] for j in jobs
                             if j is not None)
                    arr = np.zeros((K, 12, width, tm), np.int32)
                    has_runs = any(j is not None and j.get("runs")
                                   is not None for j in jobs)
                    rarr = None
                    if has_runs:
                        from ..mergetree.oppack import RUN_K
                        rarr = np.zeros((K, 4, width, tm, RUN_K),
                                        np.int32)
                    for k, j in enumerate(jobs):
                        if j is None:
                            aligned[k].append(self._empty_job(g, width))
                            continue
                        c = j["cols"]
                        arr[k, :, :c.shape[1], :c.shape[2]] = c
                        if rarr is not None and j.get("runs") is not None:
                            r = j["runs"]
                            rarr[k, :, :r.shape[1], :r.shape[2], :] = r
                        aligned[k].append(j)
                    xs.append(self._place_cols(arr, lane_axis=2))
                    rxs.append(None if rarr is None else
                               self._place_cols(rarr, lane_axis=2))
                return ids, xs, rxs, aligned

            def stack_lww_jobs(job_lists):
                ids = sorted({j["bucket"] for jl in job_lists
                              for j in jl})
                xs, states = [], []
                aligned: List[List[dict]] = [[] for _ in wins]
                for b in ids:
                    bucket = self.lww.buckets[b]
                    jobs = [next((j for j in jl if j["bucket"] == b),
                                 None) for jl in job_lists]
                    tm = max(j["cols"].shape[2] for j in jobs
                             if j is not None)
                    arr = np.zeros((K, 6, bucket.lanes, tm), np.int32)
                    arr[:, 1] = -1
                    arr[:, 2] = -1
                    for k, j in enumerate(jobs):
                        if j is None:
                            aligned[k].append(self._empty_job(
                                b, bucket.lanes))
                            continue
                        c = j["cols"]
                        arr[k, :, :c.shape[1], :c.shape[2]] = c
                        aligned[k].append(j)
                    xs.append(self._place_cols(arr, lane_axis=2))
                    states.append(bucket.state)
                return ids, xs, states, aligned

            m_ids, merge_xs, runs_xs, m_aligned = stack_group_jobs(
                [wd["merge_jobs"] for wd in wins])
            l_ids, lww_xs, lww_states, l_aligned = stack_lww_jobs(
                [wd["lww_jobs"] for wd in wins])
            # Page-id tables + pre-ring scalars per union group, staged
            # at dispatch (host-authoritative under one-in-flight).
            pg = self.merge.pages
            group_info: Dict[int, dict] = {}
            pids_l, counts_l, mins_l, seqs_l = [], [], [], []
            for gi, g in enumerate(m_ids):
                grp = self.merge.flush_groups[g]
                n_pad, pids, counts, mins, seqs = \
                    self.merge._stage_paged_group(grp.keys)
                assert n_pad == self.merge.flush_groups[g].lanes
                group_info[g] = {"keys": list(grp.keys), "pids": pids}
                pids_l.append(pids)
                counts_l.append(counts)
                mins_l.append(mins)
                seqs_l.append(seqs)

        fused = self._mega_fused_mode()
        stats_on = device_stats.enabled()
        donate = pg.donate
        fn = serve_step.serve_megakernel if donate \
            else serve_step.serve_megakernel_keep
        name = "serve.megakernel" if donate else "serve.megakernel_keep"
        tx_dev = self._place_cols(tx, lane_axis=2)

        def _dispatch(mode):
            with compile_ledger.track(name, fn):
                return fn(self.tstate, pg.pool, tuple(lww_states),
                          tx_dev, tuple(pids_l), tuple(counts_l),
                          tuple(mins_l), tuple(seqs_l), tuple(merge_xs),
                          tuple(lww_xs), tuple(runs_xs), mode, stats_on)

        with tracing.span("serving.dispatch", hist="serving.dispatch"):
            try:
                (self.tstate, pool2, new_lww, flats_dev, msns_dev,
                 pre_views) = _dispatch(fused)
            except Exception as err:  # noqa: BLE001 — degrade, not crash
                # The pallas phases failed to lower: fall back to the
                # scan op-phase INSIDE the same megakernel (still one
                # dispatch per ring). A post-lowering failure may have
                # consumed the donated carry — probe and re-raise, as
                # in _dispatch_burst_chunk.
                def _gone(tree):
                    leaf = jax.tree_util.tree_leaves(tree)
                    return bool(leaf) and bool(
                        getattr(leaf[0], "is_deleted", bool)())
                if (not fused or _gone(self.tstate) or _gone(pg.pool)
                        or any(map(_gone, lww_states))):
                    raise
                import logging
                increment("serving.megakernel_fallbacks")
                logging.getLogger(__name__).warning(
                    "megakernel pallas phases failed at K=%d; degrading "
                    "to the in-kernel scan op-phase (%r)", K, err)
                self._fused_serve = False
                self.megakernel_interpret = False
                (self.tstate, pool2, new_lww, flats_dev, msns_dev,
                 pre_views) = _dispatch(False)

        pg.adopt_pool(pool2)
        for b, post in zip(l_ids, new_lww):
            self.lww.buckets[b].state = post
        shared = {"wins": wins, "pre": list(pre_views),
                  "groups": group_info, "order": list(m_ids)}
        for k, wd in enumerate(wins):
            wd["merge_jobs"] = m_aligned[k]
            wd["lww_jobs"] = l_aligned[k]
            wd["stats"] = stats_on
            wd["noop_skip"] = True
            wd["paged"] = True
            wd["paged_shared"] = shared
            for j in wd["merge_jobs"] + wd["lww_jobs"]:
                # The carry was donated (or the keep twin holds the pre
                # views in its readback): per-window bucket pre states
                # never exist on this path.
                j["pre"] = None
            wd["msn32_dev"] = msns_dev[k]
        increment("serving.megakernel_rings")
        increment("serving.megakernel_windows", K)
        increment("serving.bursts")
        increment("serving.burst_windows", K)

        entry = {"burst": wins, "n_windows": K,
                 "trace_ctx": wins[-1]["trace_ctx"]}
        import threading

        def fetch():
            try:
                entry["flat"] = np.asarray(flats_dev)  # [K, flat] D2H
            except Exception as err:  # noqa: BLE001 — surface at join
                entry["error"] = err

        entry["thread"] = threading.Thread(target=fetch, daemon=True)
        entry["thread"].start()
        self._ring.append(entry)

    def _assess_windows(self, parsed, n_windows: int,
                        merge_all, win_m, chan_ok, chan_b, chan_l,
                        win_l, lchan_ok, lchan_b, lchan_l,
                        start_w: int = 0):
        """Per-window (risky, donate_ok) from the host occupancy hints.

        risky[w]: some staged lane's ROW fit cannot be proven —
        `hint + 2*inserts + 8 > capacity` (merge, each op adds at most 2
        rows) or `hint + ops + 4 > capacity` key slots (LWW). Risky
        windows dispatch synchronously so their likely fold/rescue runs
        the cheap empty-ring recovery.

        donate_ok[w]: not risky AND insert-only merge traffic — removes
        touch the overlap ring and annotates the anno ring, neither
        bounded by the count hint, so those windows keep their pre
        states (their rare exhaustion overflow needs the rollback). The
        margins mirror the recovery paths' +8 re-run slack convention.

        The bound ACCUMULATES across this flush's windows (from
        `start_w`, where earlier windows' charges already live in
        hint_pending): window w's fit proof counts windows start_w..w-1
        worst-case rows on the shared lanes, because none of them will
        have confirmed occupancy before w dispatches — with fused bursts
        a whole run of windows dispatches in one scan before ANY plane
        comes back, so a per-window-only bound would under-count deep
        docs and break the donated-dispatch soundness invariant."""
        from . import pump as P
        cols = parsed.cols
        risky = np.zeros(n_windows, bool)
        donate_ok = np.ones(n_windows, bool)
        acc_m: Dict[int, np.ndarray] = {}
        acc_l: Dict[int, np.ndarray] = {}
        mk = cols[P.MKIND, merge_all] if merge_all.size else None
        paged = self.merge.paged
        for w in range(start_w, n_windows):
            if mk is not None and paged:
                # R10: paged merge has no row-fit risk (flush_lane_for
                # pre-grew pages for the flush's worst case) but
                # non-insert traffic still forfeits donation — removes
                # and annotates touch the overlap/anno rings, whose
                # exhaustion needs the pre views for rollback. The
                # megakernel keeps pre views in its own readback, so
                # this only routes the window to an immediate
                # dispatch+drain (nothing stacks behind a likely
                # rescue).
                ws = chan_ok & (win_m == w)
                if ws.any() and np.any(mk[ws] != 1):
                    risky[w] = True
            elif mk is not None:
                ws = chan_ok & (win_m == w)
                if ws.any():
                    if np.any(mk[ws] != 1):
                        donate_ok[w] = False
                    for b in np.unique(chan_b[ws]).tolist():
                        bucket = self.merge.buckets[b]
                        bsel = ws & (chan_b == b)
                        ins = np.bincount(chan_l[bsel & (mk == 1)],
                                          minlength=bucket.lanes)
                        touched = np.unique(chan_l[bsel])
                        acc = acc_m.setdefault(
                            b, np.zeros(bucket.lanes, np.int64))
                        bound = bucket.count_hint[touched] \
                            + bucket.hint_pending[touched] \
                            + acc[touched]
                        if np.any(bound + 2 * ins[touched] + 8
                                  > bucket.capacity):
                            risky[w] = True
                        acc += 2 * ins
            if lchan_ok.size:
                ws = lchan_ok & (win_l == w)
                if ws.any():
                    for b in np.unique(lchan_b[ws]).tolist():
                        bucket = self.lww.buckets[b]
                        bsel = ws & (lchan_b == b)
                        per = np.bincount(lchan_l[bsel],
                                          minlength=bucket.lanes)
                        touched = np.unique(lchan_l[bsel])
                        acc = acc_l.setdefault(
                            b, np.zeros(bucket.lanes, np.int64))
                        bound = bucket.count_hint[touched] \
                            + bucket.hint_pending[touched] \
                            + acc[touched]
                        if np.any(bound + per[touched] + 4
                                  > bucket.capacity):
                            risky[w] = True
                        acc += per
        donate_ok &= ~risky
        return risky, donate_ok

    def _finish_window(self, ctx) -> None:
        """The post-fetch half of a fast window: seq/msn distribution,
        invariant checks, nack emission, (rare) overflow recovery."""
        from . import pump as P
        parsed = ctx["parsed"]
        cols = parsed.cols
        B, T = ctx["B"], ctx["T"]
        rows, lanes, slot = ctx["rows"], ctx["lanes"], ctx["slot"]
        flat = ctx["flat"]
        merge_jobs, lww_jobs = ctx["merge_jobs"], ctx["lww_jobs"]

        bt = B * T

        def u32(lo, hi):
            return ((hi.astype(np.int64) << 16)
                    | (lo.astype(np.int64) & 0xFFFF)).astype(np.int64)

        # Narrow layout (serve_step.serve_window): int16 deltas + int32
        # lane scalars as (lo, hi) halves + [msn_ok | overflow bits].
        seq_d = flat[:bt].reshape(B, T).astype(np.int64)
        msn_d = flat[bt:2 * bt].reshape(B, T).astype(np.int64)
        fl_bt = flat[2 * bt:3 * bt].reshape(B, T)
        p = 3 * bt
        next_seq = u32(flat[p:p + B], flat[p + B:p + 2 * B])
        msn_base = u32(flat[p + 2 * B:p + 3 * B],
                       flat[p + 3 * B:p + 4 * B])
        tailbits = flat[p + 4 * B:]
        nm, nl = len(merge_jobs), len(lww_jobs)
        msn_ok = tailbits[0]
        bits = tailbits[1:2 + nm + nl]
        # Per-lane overflow planes (one int16 per staged bucket lane,
        # serve_step.serve_window layout): merge jobs then LWW jobs, each
        # lanes_n wide — recovery never touches the (possibly donated)
        # post states. The occupancy (count) planes follow in the same
        # order.
        plane_total = sum(j["lanes_n"] for j in merge_jobs) \
            + sum(j["lanes_n"] for j in lww_jobs)
        planes = tailbits[2 + nm + nl:2 + nm + nl + plane_total]
        cnt_planes = tailbits[2 + nm + nl + plane_total:
                              2 + nm + nl + 2 * plane_total]
        tail_base = 2 + nm + nl + 2 * plane_total
        if ctx.get("paged"):
            # Megakernel scalar-adoption plane (R10): each page group's
            # post count/min_seq/seq as exact int32 halves — the int16
            # occupancy planes above can wrap for a large group, so
            # scalar adoption and the stats mirror read these.
            m_tot = sum(j["lanes_n"] for j in merge_jobs)
            paged16 = tailbits[tail_base:tail_base + 6 * m_tot]
            tail_base += 6 * m_tot
            ctx["_paged_scalars"] = []
            off = 0
            for job in merge_jobs:
                n = job["lanes_n"]
                seg = paged16[off:off + 6 * n]
                off += 6 * n
                ctx["_paged_scalars"].append(
                    (u32(seg[:n], seg[n:2 * n]),
                     u32(seg[2 * n:3 * n], seg[3 * n:4 * n]),
                     u32(seg[4 * n:5 * n], seg[5 * n:6 * n])))
        # The device telemetry plane (present only when this window
        # dispatched with stats): N_SERVE int32 slots as lo/hi halves.
        stats16 = tailbits[tail_base:] if ctx.get("stats") else None

        q_m = np.fromiter(self._ring_fixup, np.int64,
                          len(self._ring_fixup)) \
            if self._ring_fixup else None
        q_l = np.fromiter(self._ring_fixup_lww, np.int64,
                          len(self._ring_fixup_lww)) \
            if self._ring_fixup_lww else None

        # Exact occupancy refresh from this window's own result: the
        # confirmed base adopts the post-window counts for the lanes THIS
        # window staged (never the whole plane — lanes seeded/alloc'd by
        # later flushes while this window was in flight have newer hints)
        # and this window's staged-op bound leaves the pending set — the
        # donation/deferral gate never decays pessimistic. Runs BEFORE
        # recovery, whose put_rows re-hint any rolled-back lanes;
        # quarantined lanes keep their recovered base (this window's
        # device counts for them describe discarded state). When a
        # recovery ran since this window dispatched (gen moved), a staged
        # lane may have been freed and REALLOCATED to another channel —
        # refresh/deduct only lanes still owned by the staged key.
        gen_same = ctx.get("gen") == self._recovery_gen

        def _owned_rows(bucket, job):
            """Per-row mask: the staged channel still owns the lane (a
            recovery may have freed + reallocated it while this window
            was in flight)."""
            return np.fromiter(
                (bucket.used[int(l)] == self._pump_chan[int(c)]
                 for l, c in zip(job["lanes"], job["chan"])), bool,
                job["lanes"].size)

        cnt_off = 0
        for job in merge_jobs:
            n = job["lanes_n"]
            if ctx.get("paged"):
                # R10: no bucket hints to refresh — paged occupancy is
                # the host page scalars, adopted at the ring's LAST
                # window from the exact paged16 plane below.
                cnt_off += n
                continue
            bucket = self.merge.buckets[job["bucket"]]
            fresh = cnt_planes[cnt_off:cnt_off + n].astype(np.int64)
            cnt_off += n
            pend_lanes = job["lanes"]
            lanes_j = np.unique(pend_lanes)
            if not gen_same:
                own = _owned_rows(bucket, job)
                job["owned"] = own
                pend_lanes = pend_lanes[own]
                lanes_j = np.unique(pend_lanes)
            if q_m is not None:
                qlanes = np.unique(job["lanes"][np.isin(job["chan"], q_m)])
                lanes_j = np.setdiff1d(lanes_j, qlanes)
            bucket.count_hint[lanes_j] = fresh[lanes_j]
            np.subtract.at(bucket.hint_pending, pend_lanes, 2)
            np.maximum(bucket.hint_pending, 0,
                       out=bucket.hint_pending)
        for job in lww_jobs:
            n = job["lanes_n"]
            bucket = self.lww.buckets[job["bucket"]]
            fresh = cnt_planes[cnt_off:cnt_off + n].astype(np.int64)
            cnt_off += n
            pend_lanes = job["lanes"]
            lanes_j = np.unique(pend_lanes)
            if not gen_same:
                own = _owned_rows(bucket, job)
                job["owned"] = own
                pend_lanes = pend_lanes[own]
                lanes_j = np.unique(pend_lanes)
            if q_l is not None:
                qlanes = np.unique(job["lanes"][np.isin(job["chan"], q_l)])
                lanes_j = np.setdiff1d(lanes_j, qlanes)
            bucket.count_hint[lanes_j] = fresh[lanes_j]
            np.subtract.at(bucket.hint_pending, pend_lanes, 1)
            np.maximum(bucket.hint_pending, 0,
                       out=bucket.hint_pending)
        admitted = seq_d >= 0
        seq_bt = np.where(admitted, next_seq[:, None] - seq_d, 0)
        if msn_ok:
            msn_bt = np.where(admitted, msn_base[:, None] + msn_d, 0)
        else:
            # A catch-up msn jump exceeded the int16 delta: fetch the
            # exact int32 plane (rare second RPC).
            msn_bt = np.asarray(ctx["msn32_dev"]).astype(np.int64)
            msn_bt = np.where(admitted, msn_bt, 0)
        if ctx.get("paged"):
            # The megakernel ring's LAST window settles every page
            # group; it rebuilds flagged docs' op streams from ALL K
            # windows, so each window stashes its decoded seq/msn
            # planes here.
            ctx["_seq_bt"] = seq_bt
            ctx["_msn_bt"] = msn_bt
        if bits[0]:
            raise RuntimeError("ticket client table overflow despite "
                               "pre-flush growth — invariant violation")

        if stats16 is not None:
            s_n = device_stats.N_SERVE
            dev_vec = u32(stats16[:s_n], stats16[s_n:2 * s_n])
            host_vec = self._mirror_window_stats(
                ctx, seq_bt, fl_bt, admitted, planes,
                cnt_planes, merge_jobs, lww_jobs)
            device_stats.fold_serve(dev_vec, host_vec)

        ctx["row_seq"][ctx["idx"]] = seq_bt[lanes, slot]
        ctx["row_msn"][ctx["idx"]] = msn_bt[lanes, slot]
        # Annotate LWW ordering needs each merge op's assigned seq.
        block = ctx["block"]
        for job in merge_jobs:
            block.seqs[job["op_ids"] - ctx["mbase"]] = \
                seq_bt[job["doc_lane"], job["slot"]]

        # Nacks (rare): materialize the offending message from its span.
        row_flags = fl_bt[lanes, slot]
        for k in np.flatnonzero(row_flags != 0).tolist():
            from .wire import document_message_from_dict
            r = int(rows[k])
            buf = parsed.bufs[int(cols[P.BUF, r])]
            msg = document_message_from_dict(json.loads(
                buf[int(cols[P.MSTART, r]):int(cols[P.MEND, r])]))
            doc_id = self._pump_docs[int(cols[P.DOC, r])]
            dl = self.docs[doc_id]
            reason = ("client not joined" if row_flags[k] & 2
                      else "refSeq below minimum sequence number")
            self.nack(doc_id,
                      dl.ordinals.get(int(cols[P.CLIENT, r]), ""),
                      Nack(msg, int(next_seq[dl.lane]) - 1,
                           NackContent(NACK_BAD_REF_SEQ, reason)))

        # Overflow recovery (rare): roll flagged lanes back to their
        # pre-window rows and reuse the batched slow-path recovery. The
        # span is unconditional — a flush with nothing to rescue records
        # a ~0 µs stage, so captures always show the stage's cost.
        # Ring-aware: lanes recovered while LATER windows are in flight
        # quarantine their channels — those windows' rows for them
        # re-apply host-side here (fixup), in dispatch order, instead of
        # trusting device results computed from pre-recovery rows.
        with tracing.span("serving.fold_rescue", parent=ctx.get("trace_ctx"),
                          hist="serving.fold_rescue") as _frsp:
            bit_i = 1  # bits[0] is the ticket-table invariant bit
            recovered = 0
            plane_off = 0
            # Quarantine direction: anything dispatched AFTER this
            # window — later ring entries, staged windows, or the rest
            # of this window's own burst (burst_more) — holds device
            # results computed from pre-recovery rows.
            ring_behind = bool(self._ring) or bool(self._staged) \
                or bool(ctx.get("burst_more"))
            fixup_merge: Dict[tuple, List[HostOp]] = {}
            fixup_lww: Dict[tuple, List[tuple]] = {}
            for gi, job in enumerate(merge_jobs):
                n = job["lanes_n"]
                over = planes[plane_off:plane_off + n] != 0
                plane_off += n
                if ctx.get("paged"):
                    # R10: overflow is sticky in the megakernel's scan
                    # carry, so the LAST window's plane is the union of
                    # every flagged doc and all settlement (scalar
                    # adoption, trailing-page release, rollback+rescue)
                    # happens there — with nothing in flight behind it
                    # (one-in-flight ring), so no quarantine direction
                    # exists on this path.
                    bit_i += 1
                    if not ctx.get("burst_more"):
                        # Named settle stage: the megakernel ring's
                        # scalar adoption + page release + rescue is
                        # the path's fourth serving sub-span (pack /
                        # dispatch / readback / settle), so ring
                        # captures attribute settlement cost instead
                        # of folding it into fold_rescue.
                        with tracing.span("serving.settle",
                                          hist="serving.settle") as _ssp:
                            got = self._finish_paged_group(
                                ctx, gi, job, over)
                            if got:
                                _ssp.set(rescued=True)
                            recovered += got
                    continue
                qsel = np.isin(job["chan"], q_m) if q_m is not None \
                    else None
                if bits[bit_i]:
                    qlanes = set(job["lanes"][qsel].tolist()) \
                        if qsel is not None else set()
                    flagged = sorted(
                        {int(i) for i in job["lanes"].tolist()
                         if over[i] and i not in qlanes})
                    if flagged:
                        self._recover_fast_merge(
                            parsed, job, seq_bt, msn_bt, flagged,
                            quarantine=ring_behind)
                        recovered += 1
                bit_i += 1
                if qsel is not None and qsel.any():
                    self._collect_merge_fixup(fixup_merge, parsed, job,
                                              seq_bt, msn_bt, qsel)
            for job in lww_jobs:
                n = job["lanes_n"]
                over = planes[plane_off:plane_off + n] != 0
                plane_off += n
                qsel = np.isin(job["chan"], q_l) if q_l is not None \
                    else None
                if bits[bit_i]:
                    qlanes = set(job["lanes"][qsel].tolist()) \
                        if qsel is not None else set()
                    flagged = sorted(
                        {int(i) for i in job["lanes"].tolist()
                         if over[i] and i not in qlanes})
                    if flagged:
                        self._recover_fast_lww(parsed, job, seq_bt,
                                               flagged,
                                               quarantine=ring_behind)
                        recovered += 1
                bit_i += 1
                if qsel is not None and qsel.any():
                    self._collect_lww_fixup(fixup_lww, parsed, job,
                                            seq_bt, qsel)
            if fixup_merge:
                increment("serving.ring_fixups", len(fixup_merge))
                self._recovery_gen += 1  # the re-apply itself may promote
                self.merge._apply_streams(fixup_merge)
            if fixup_lww:
                increment("serving.ring_fixups", len(fixup_lww))
                self._recovery_gen += 1
                self.lww._apply_window(fixup_lww)
            if recovered:
                _frsp.set(recovered_jobs=recovered)

    def _finish_paged_group(self, ctx, gi: int, job: dict,
                            over: np.ndarray) -> int:
        """Settle one page group at its megakernel ring's LAST window
        (R10): adopt the exact post scalars for clean docs (the host
        page scalars are authoritative between flushes), release their
        dead trailing pages, and roll back + host-rescue flagged docs
        with their ops from ALL K windows — overflow is sticky in the
        scan carry, so a doc flagged at window k has every later
        window's device rows voided too, and the rescue replays the
        whole ring's stream against the rolled-back pre-ring view.
        Returns the number of rescue passes run (0 or 1)."""
        shared = ctx.get("paged_shared")
        info = None if shared is None \
            else shared["groups"].get(job["bucket"])
        if info is None:
            return 0
        pg = self.merge.pages
        keys = info["keys"]
        n = len(keys)
        counts, mins, seqs = ctx["_paged_scalars"][gi]
        over_n = over[:n]
        good = np.flatnonzero(~over_n)
        if good.size:
            gkeys = [keys[j] for j in good.tolist()]
            pg.adopt_scalars(gkeys, counts[good].astype(np.int32),
                             mins[good].astype(np.int32),
                             seqs[good].astype(np.int32))
            ops_per = np.zeros(n, np.int64)
            for wd in shared["wins"]:
                jw = wd["merge_jobs"][gi]
                if jw["lanes"].size:
                    np.add.at(ops_per, jw["lanes"], 1)
            for j in good.tolist():
                key = keys[j]
                pg.ops_since_compact[key] = \
                    pg.ops_since_compact.get(key, 0) + int(ops_per[j])
            pg.release_trailing_many(gkeys)
        flagged = np.flatnonzero(over_n).tolist()
        if not flagged:
            return 0
        items = self._collect_paged_ring_ops(shared, gi, keys)
        self._recovery_gen += 1
        increment("serving.recovery_dispatches")
        self.merge._recover_paged(keys, items, info["pids"],
                                  shared["pre"][gi], flagged)
        return 1

    def _collect_paged_ring_ops(self, shared, gi: int, keys):
        """HostOp streams for one page group across its megakernel
        ring's K windows, in window order — _recover_fast_merge's
        stream rebuild, widened to the whole ring entry."""
        from . import pump as P
        ops_by: Dict[int, List[HostOp]] = {}
        for wd in shared["wins"]:
            job = wd["merge_jobs"][gi]
            rows_j = job["rows"]
            if rows_j is None or not len(rows_j):
                continue
            cols = wd["parsed"].cols
            seq_bt = wd["_seq_bt"]
            msn_bt = wd["_msn_bt"]
            for k, lane in enumerate(job["lanes"].tolist()):
                r = int(rows_j[k])
                # seq/msn were assigned by the ticket pass regardless
                # of the merge overflow; reuse them for the re-run.
                seq = int(seq_bt[job["doc_lane"][k], job["slot"][k]])
                msn = int(msn_bt[job["doc_lane"][k], job["slot"][k]])
                if seq <= 0:
                    continue
                ops_by.setdefault(int(lane), []).append(HostOp(
                    kind=int(cols[P.MKIND, r]), seq=seq,
                    ref_seq=int(cols[P.REFSEQ, r]),
                    client=int(cols[P.CLIENT, r]),
                    pos1=int(cols[P.POS1, r]), pos2=int(cols[P.POS2, r]),
                    op_id=int(job["op_ids"][k]),
                    new_len=int(cols[P.CHARLEN, r]),
                    local_seq=0, msn=msn))
        return [(key, ops_by.get(j, [])) for j, key in enumerate(keys)]

    def _mirror_window_stats(self, ctx, seq_bt, fl_bt, admitted,
                             planes, cnt_planes, merge_jobs, lww_jobs):
        """The HOST-derived mirror of one window's device telemetry
        plane (telemetry/device_stats.SERVE_SLOTS order), re-deriving
        every countable slot from the staged op columns + the decoded
        ticket results with exactly the admission logic the device
        program applies (nack masking, INSERT_RUN mispredict voiding,
        burst padding skips). device-vs-host reconciliation is then an
        exact counter diff — the obs-smoke gate. Vectorized numpy over
        the window's staged columns: microseconds against the device
        program it mirrors."""
        noop_skip = bool(ctx.get("noop_skip"))
        kinds = np.zeros(6, np.int64)  # INSERT..INSERT_RUN
        skips = 0
        for job in merge_jobs:
            c = job.get("cols")
            if c is None:
                if noop_skip:
                    skips += 1  # union-bucket padding: all-NOOP plane
                continue
            kind = c[0]
            ok = (kind != OpKind.NOOP) & (seq_bt[c[10], c[11]] > 0)
            r = job.get("runs")
            if r is not None:
                expected = r[0] > 0
                sub_ok = seq_bt[r[2], r[3]] > 0
                mispredict = (kind == OpKind.INSERT_RUN) & np.any(
                    expected & ~sub_ok, axis=-1)
                ok &= ~mispredict
            kk = kind[ok]
            kinds += np.bincount(kk, minlength=7)[1:7]
            if noop_skip and kk.size == 0:
                skips += 1
        lww_n = 0
        for job in lww_jobs:
            c = job.get("cols")
            if c is None:
                if noop_skip:
                    skips += 1
                continue
            # LwwKind.NOOP == 0 (server/lww_kernel.py)
            ok = (c[0] != 0) & (seq_bt[c[4], c[5]] > 0)
            n_ok = int(ok.sum())
            lww_n += n_ok
            if noop_skip and n_ok == 0:
                skips += 1
        merge_total = sum(j["lanes_n"] for j in merge_jobs)
        if ctx.get("_paged_scalars") is not None:
            # R10: the device sums the EXACT int32 group counts; the
            # int16 count planes can wrap for a large page group, so
            # the mirror reads the decoded paged16 scalars instead.
            merge_cnt = sum(int(c.sum())
                            for c, _m, _s in ctx["_paged_scalars"])
        else:
            merge_cnt = int(cnt_planes[:merge_total].astype(np.int64)
                            .sum())
        host_vec = np.array(list(kinds) + [
            lww_n,
            int(admitted.sum()),
            int((fl_bt & 1).astype(bool).sum()),
            int(((fl_bt >> 1) & 1).astype(bool).sum()),
            int((planes[:merge_total] != 0).sum()),
            int((planes[merge_total:] != 0).sum()),
            skips,
            # Lane-fill gauges: the device sums the same count planes
            # that ride this result, so the mirror is the plane sum.
            merge_cnt,
            int(cnt_planes[merge_total:].astype(np.int64).sum()),
        ], np.int64)
        return host_vec

    def _build_merge(self, parsed, rows, lanes, slot,
                     mbase, chan_ok, chan_b, chan_l, flush_rows=None):
        """Per-bucket merge window staging ([12, lanes, Tm]: 10 PackedOps
        columns + doc_idx + t_idx, one array => one H2D); returns job
        records carrying what the (rare) recovery path needs.
        `flush_rows` overrides the live flush's merge-row universe (the
        staged-window degrade restage, which may run after a LATER flush
        overwrote self._flush_merge_rows)."""
        from . import pump as P
        cols = parsed.cols
        if flush_rows is None:
            flush_rows = self._flush_merge_rows
        in_window = np.isin(flush_rows, rows)
        sel = in_window & chan_ok
        jobs = []
        if not sel.any():
            return jobs
        mrows = flush_rows[sel]
        mb = chan_b[sel]
        ml = chan_l[sel]
        cpos = _cumcount(cols[P.CHAN, mrows])
        op_ids = mbase + np.flatnonzero(sel)
        # Window-local position of each selected merge row (rows sorted).
        wrow = np.searchsorted(rows, mrows)
        paged = self.merge.paged
        for b in np.unique(mb).tolist():
            bsel = mb == b
            if paged:
                # R10: b is a flush-group id; the plane width is the
                # group's pow2-padded member count and there is no pre
                # state to snapshot — the megakernel returns the
                # gathered pre views in its own readback.
                group_lanes = self.merge.flush_groups[b].lanes
                pre_state = None
            else:
                bucket = self.merge.buckets[b]
                group_lanes = bucket.lanes
                pre_state = bucket.state
            rl = ml[bsel]
            rr = mrows[bsel]
            doc_lane = lanes[wrow[bsel]]
            tslot = slot[wrow[bsel]]
            b_kind = cols[P.MKIND, rr]
            b_client = cols[P.CLIENT, rr]
            b_ref = cols[P.REFSEQ, rr]
            b_pos1 = cols[P.POS1, rr]
            b_len = cols[P.CHARLEN, rr]
            runs_rc = None
            if self.pack_runs:
                from ..mergetree.oppack import RUN_K, RUN_MIN
                rp, sub, head, tail = _pack_lane_runs(
                    rl, b_kind, b_client, b_ref, b_pos1, b_len,
                    RUN_K, RUN_MIN)
                rp = rp.astype(np.int64)
                is_member = sub >= 0
            else:
                rp = cpos[bsel]
                sub = np.full(rr.size, -1, np.int64)
                head = tail = np.ones(rr.size, bool)
                is_member = np.zeros(rr.size, bool)
            Tm = _bucket(int(rp.max()) + 1 if rr.size else 1,
                         self.t_buckets)
            mc = np.zeros((12, group_lanes, Tm), np.int32)
            # Layout matches serve_step.serve_window: kind seq ref client
            # pos1 pos2 op_id new_len local_seq msn doc_idx t_idx.
            # Run slots: the stream-FIRST member provides pos1/ref/client
            # (writes below land head-last per slot via masked ordering),
            # the stream-LAST member provides doc_idx/t_idx (seq/msn
            # gather source); kind becomes INSERT_RUN, op_id -1, new_len
            # the member total.
            hsel = head  # plain rows AND run heads define op columns
            mc[0, rl[hsel], rp[hsel]] = np.where(
                is_member[hsel], OpKind.INSERT_RUN, b_kind[hsel])
            mc[2, rl[hsel], rp[hsel]] = b_ref[hsel]
            mc[3, rl[hsel], rp[hsel]] = b_client[hsel]
            mc[4, rl[hsel], rp[hsel]] = b_pos1[hsel]
            mc[5, rl[hsel], rp[hsel]] = cols[P.POS2, rr][hsel]
            mc[6, rl[hsel], rp[hsel]] = np.where(
                is_member[hsel], -1, op_ids[bsel][hsel])
            run_total = np.zeros(rr.size, np.int64)
            if is_member.any():
                # total member length per (lane, slot), read back per row
                key = rl * Tm + rp
                sums = np.zeros(group_lanes * Tm, np.int64)
                np.add.at(sums, key[is_member], b_len[is_member])
                run_total = sums[key]
            mc[7, rl[hsel], rp[hsel]] = np.where(
                is_member[hsel], run_total[hsel], b_len[hsel])
            tsel = tail
            mc[10, rl[tsel], rp[tsel]] = doc_lane[tsel]
            mc[11, rl[tsel], rp[tsel]] = tslot[tsel]
            if is_member.any():
                rc = np.zeros((4, group_lanes, Tm, RUN_K), np.int32)
                msel = is_member
                rc[0, rl[msel], rp[msel], sub[msel]] = b_len[msel]
                rc[1, rl[msel], rp[msel], sub[msel]] = op_ids[bsel][msel]
                rc[2, rl[msel], rp[msel], sub[msel]] = doc_lane[msel]
                rc[3, rl[msel], rp[msel], sub[msel]] = tslot[msel]
                runs_rc = rc
            jobs.append({"bucket": b, "pre": pre_state, "cols": mc,
                         "runs": runs_rc, "lanes_n": group_lanes,
                         "chan": cols[P.CHAN, rr],
                         "rows": rr, "lanes": rl, "op_ids": op_ids[bsel],
                         "doc_lane": doc_lane, "slot": tslot})
        return jobs

    def _build_lww(self, parsed, rows, lanes, slot,
                   vbase, chan_ok, chan_b, chan_l):
        """Per-bucket LWW staging ([6, lanes, Tm]: kind key val delta
        doc_idx t_idx)."""
        from . import pump as P
        cols = parsed.cols
        lk = self.lww.lk
        flush_rows = self._flush_lww_rows
        in_window = np.isin(flush_rows, rows)
        sel = in_window & chan_ok
        jobs = []
        if not sel.any():
            return jobs
        lrows = flush_rows[sel]
        lb = chan_b[sel]
        ll = chan_l[sel]
        cpos = _cumcount(cols[P.CHAN, lrows])
        val_ids = vbase + np.flatnonzero(sel)
        wrow = np.searchsorted(rows, lrows)
        for b in np.unique(lb).tolist():
            bsel = lb == b
            bucket = self.lww.buckets[b]
            Tm = _bucket(int(cpos[bsel].max()) + 1, self.t_buckets)
            lc = np.zeros((6, bucket.lanes, Tm), np.int32)
            lc[1] = -1
            lc[2] = -1
            rl = ll[bsel]
            rp = cpos[bsel]
            rr = lrows[bsel]
            lc[0, rl, rp] = cols[P.MKIND, rr]
            kord = cols[P.POS1, rr]
            lc[1, rl, rp] = np.where(kord >= 0, self._lww_key_map[kord],
                                     -1)
            is_set = cols[P.MKIND, rr] == lk.LwwKind.SET
            lc[2, rl, rp] = np.where(is_set, val_ids[bsel], -1)
            lc[3, rl, rp] = cols[P.POS2, rr]
            doc_lane = lanes[wrow[bsel]]
            tslot = slot[wrow[bsel]]
            lc[4, rl, rp] = doc_lane
            lc[5, rl, rp] = tslot
            jobs.append({"bucket": b, "pre": bucket.state, "cols": lc,
                         "lanes_n": bucket.lanes, "chan": cols[P.CHAN, rr],
                         "rows": rr, "lanes": rl, "val_ids": val_ids[bsel],
                         "doc_lane": doc_lane, "slot": tslot})
        return jobs

    def _recover_fast_merge(self, parsed, job, seq_bt, msn_bt,
                            flagged: List[int],
                            quarantine: bool = False) -> None:
        """A merge bucket overflowed in a fast window: rebuild HostOp
        streams for the flagged lanes from the pump columns, roll those
        lanes back to their pre-window rows, and run the slow path's
        batched recovery. `quarantine=True` (windows behind this one are
        still in flight) additionally quarantines the recovered channels:
        the later windows' device results for these lanes are void, and
        their rows re-apply host-side at each window's own drain."""
        from . import pump as P
        cols = parsed.cols
        b = job["bucket"]
        bucket = self.merge.buckets[b]
        flag_set = set(flagged)
        own = job.get("owned")  # set by _finish_window when gen moved
        tm = jax.tree_util.tree_map
        lane_ops: Dict[int, List[HostOp]] = {}
        for k, i in enumerate(job["lanes"].tolist()):
            if i not in flag_set:
                continue
            if own is not None and not own[k]:
                # A recovery freed + reallocated this lane while the
                # window was in flight: the plane bit describes the OLD
                # channel's discarded state — never roll back or re-run
                # over the lane's new owner.
                continue
            r = int(job["rows"][k])
            # seq/msn were assigned by the ticket pass regardless of the
            # merge overflow; reuse them for the re-run.
            seq = int(seq_bt[job["doc_lane"][k], job["slot"][k]])
            msn = int(msn_bt[job["doc_lane"][k], job["slot"][k]])
            if seq <= 0:
                continue
            lane_ops.setdefault(i, []).append(HostOp(
                kind=int(cols[P.MKIND, r]), seq=seq,
                ref_seq=int(cols[P.REFSEQ, r]),
                client=int(cols[P.CLIENT, r]),
                pos1=int(cols[P.POS1, r]), pos2=int(cols[P.POS2, r]),
                op_id=int(job["op_ids"][k]),
                new_len=int(cols[P.CHARLEN, r]),
                local_seq=0, msn=msn))
        if not lane_ops:
            return
        self._recovery_gen += 1
        increment("serving.recovery_dispatches")
        if job["pre"] is None:
            # Donated window flagged overflow: the gate's fit proof was
            # wrong (hint bug) or the overflow was structurally
            # unpredictable (bad insert position, a nacked INSERT_RUN
            # member) and the pre rows are gone. Degrade the affected
            # channels to opaque instead of materializing corrupt state
            # — loudly.
            self._degrade_donated_merge(b, sorted(lane_ops))
            return
        if quarantine:
            for i in sorted(lane_ops):
                key = bucket.used[i]
                ch = self._chan_ord.get(key)
                if ch is not None:
                    self._ring_fixup.add(int(ch))
        idx = jnp.asarray(np.asarray(sorted(lane_ops), np.int32))
        bucket.state = tm(lambda col, p: col.at[idx].set(p[idx]),
                          bucket.state, job["pre"])
        self.merge._recover_batch(b, lane_ops)

    def _degrade_donated_merge(self, b: int, lanes: List[int]) -> None:
        import logging
        increment("sequencer.donated_overflow")
        bucket = self.merge.buckets[b]
        keys = [bucket.used[i] for i in lanes if bucket.used[i] is not None]
        logging.getLogger(__name__).error(
            "merge overflow on a DONATED window (occupancy-hint invariant "
            "break); degrading %d channel(s) to opaque: %r", len(keys),
            keys)
        for key in keys:
            # Quarantine BEFORE dropping: in-flight windows that staged
            # this channel must void their device results for it at
            # their drain (the opaque check then skips the re-apply),
            # not recover against a freed/reallocated lane.
            ch = self._chan_ord.get(key)
            if ch is not None:
                self._ring_fixup.add(int(ch))
            self.merge.drop(key)
            self.merge.overflow_drops += 1

    def _collect_merge_fixup(self, streams: Dict[tuple, List[HostOp]],
                             parsed, job, seq_bt, msn_bt,
                             qsel: np.ndarray) -> None:
        """Rows riding a quarantined channel: their lanes were rolled back
        and host-recovered by an EARLIER window's drain, so this window's
        device result for them is void — rebuild the ops as HostOp
        streams (arrival order) for the sync-faithful re-apply."""
        from . import pump as P
        cols = parsed.cols
        for k in np.flatnonzero(qsel).tolist():
            r = int(job["rows"][k])
            seq = int(seq_bt[job["doc_lane"][k], job["slot"][k]])
            msn = int(msn_bt[job["doc_lane"][k], job["slot"][k]])
            if seq <= 0:
                continue
            key = self._pump_chan[int(job["chan"][k])]
            streams.setdefault(key, []).append(HostOp(
                kind=int(cols[P.MKIND, r]), seq=seq,
                ref_seq=int(cols[P.REFSEQ, r]),
                client=int(cols[P.CLIENT, r]),
                pos1=int(cols[P.POS1, r]), pos2=int(cols[P.POS2, r]),
                op_id=int(job["op_ids"][k]),
                new_len=int(cols[P.CHARLEN, r]),
                local_seq=0, msn=msn))

    def _recover_fast_lww(self, parsed, job, seq_bt, flagged: List[int],
                          quarantine: bool = False) -> None:
        from . import pump as P
        cols = parsed.cols
        lk = self.lww.lk
        b = job["bucket"]
        bucket = self.lww.buckets[b]
        flag_set = set(flagged)
        own = job.get("owned")  # set by _finish_window when gen moved
        tm = jax.tree_util.tree_map
        lane_ops: Dict[int, List[tuple]] = {}
        for k, i in enumerate(job["lanes"].tolist()):
            if i not in flag_set:
                continue
            if own is not None and not own[k]:
                # Lane freed + reallocated while in flight (see
                # _recover_fast_merge): never touch the new owner.
                continue
            r = int(job["rows"][k])
            seq = int(seq_bt[job["doc_lane"][k], job["slot"][k]])
            if seq <= 0:
                continue
            kord = int(cols[P.POS1, r])
            kid = int(self._lww_key_map[kord]) if kord >= 0 else -1
            mk = int(cols[P.MKIND, r])
            lane_ops.setdefault(i, []).append(
                (mk, kid,
                 int(job["val_ids"][k]) if mk == lk.LwwKind.SET else -1,
                 int(cols[P.POS2, r]), seq))
        if not lane_ops:
            return
        self._recovery_gen += 1
        increment("serving.recovery_dispatches")
        if job["pre"] is None:
            import logging
            increment("sequencer.donated_overflow")
            keys = [bucket.used[i] for i in sorted(lane_ops)
                    if bucket.used[i] is not None]
            logging.getLogger(__name__).error(
                "LWW overflow on a DONATED window (occupancy-hint "
                "invariant break); degrading %d channel(s): %r",
                len(keys), keys)
            for key in keys:
                # Quarantine before dropping (see _degrade_donated_merge).
                ch = self._chan_ord.get(key)
                if ch is not None:
                    self._ring_fixup_lww.add(int(ch))
                self.lww.drop(key)
                self.lww.overflow_drops += 1
            return
        if quarantine:
            for i in sorted(lane_ops):
                key = bucket.used[i]
                ch = self._chan_ord.get(key)
                if ch is not None:
                    self._ring_fixup_lww.add(int(ch))
        idx = jnp.asarray(np.asarray(sorted(lane_ops), np.int32))
        bucket.state = tm(lambda col, p: col.at[idx].set(p[idx]),
                          bucket.state, job["pre"])
        for i, ops in lane_ops.items():
            t = _bucket(len(ops), self.t_buckets)
            self.lww._promote(b, i, ops, t)

    def _collect_lww_fixup(self, streams: Dict[tuple, List[tuple]],
                           parsed, job, seq_bt, qsel: np.ndarray) -> None:
        from . import pump as P
        cols = parsed.cols
        lk = self.lww.lk
        for k in np.flatnonzero(qsel).tolist():
            r = int(job["rows"][k])
            seq = int(seq_bt[job["doc_lane"][k], job["slot"][k]])
            if seq <= 0:
                continue
            kord = int(cols[P.POS1, r])
            kid = int(self._lww_key_map[kord]) if kord >= 0 else -1
            mk = int(cols[P.MKIND, r])
            key = self._pump_chan[int(job["chan"][k])]
            streams.setdefault(key, []).append(
                (mk, kid,
                 int(job["val_ids"][k]) if mk == lk.LwwKind.SET else -1,
                 int(cols[P.POS2, r]), seq))

    def _evict_ghosts(self, active_docs: List[str]) -> None:
        """Synthesize leaves for writers silent past client_timeout_s
        (DeliLambda._evict_ghosts, device path). With a raw-log producer
        the leave rides the log (replay-deterministic); the fallback
        appends to the in-memory backlog so the NoClient timing and
        quorum removal stay exact either way."""
        if not self.client_timeout_s:
            return
        cutoff = time.time() - self.client_timeout_s
        for doc_id in active_docs:
            dl = self.docs.get(doc_id)
            if dl is None:
                continue
            stale = [cid for cid, ts in dl.last_seen.items()
                     if ts < cutoff and cid not in dl.evicting]
            for client_id in stale:
                leave = DocumentMessage(
                    client_sequence_number=0,
                    reference_sequence_number=-1,
                    type=MessageType.CLIENT_LEAVE,
                    data=json.dumps({"clientId": client_id,
                                     "evicted": True}))
                if self.send_system is not None:
                    dl.evicting.add(client_id)
                    self.send_system(doc_id, leave)
                else:
                    dl.last_seen.pop(client_id, None)
                    self.pending.setdefault(doc_id, []).append(_Pending(
                        tk.MsgKind.LEAVE, dl.intern(client_id), 0, 0,
                        leave, None))
                    if doc_id in self._pump_known:
                        self._pump_sync_dirty.add(doc_id)

    def _take_window(self) -> Dict[str, List[_Pending]]:
        """Carve the next per-doc message chunks off the backlog: at most
        max-T-bucket messages per doc, and cut immediately AFTER a LEAVE —
        so the host can interpose the NoClient message with the scalar
        deli's exact timing (deli.py CLIENT_LEAVE tail) before the doc's
        remaining messages sequence."""
        max_t = self.t_buckets[-1]
        live: Dict[str, List[_Pending]] = {}
        for doc_id, q in list(self.pending.items()):
            if not q:
                del self.pending[doc_id]
                continue
            cut = min(len(q), max_t)
            for idx in range(cut):
                if q[idx].kind == tk.MsgKind.LEAVE:
                    cut = idx + 1
                    break
            live[doc_id] = q[:cut]
            if len(q) > cut:
                self.pending[doc_id] = q[cut:]
            else:
                del self.pending[doc_id]
        return live

    def _flush_window(self) -> None:
        live = self._take_window()
        if not live:
            return
        # Pre-size the client table: joins this window + already-known
        # ordinals must fit K (grow BEFORE the kernel, so the in-kernel
        # overflow flag is a genuine invariant violation, not a sizing bug).
        need_k = max((dl.next_ordinal for dl in self.docs.values()),
                     default=0)
        while self.k < need_k:
            self._grow_clients()

        _tkt0 = time.perf_counter()
        with tracing.span("serving.pack", hist="serving.pack",
                          stage="ticket-staging"):
            t = _bucket(max(len(q) for q in live.values()), self.t_buckets)
            b = self.lanes
            kind = np.zeros((b, t), np.int32)
            client = np.full((b, t), -1, np.int32)
            cseq = np.zeros((b, t), np.int32)
            ref = np.zeros((b, t), np.int32)
            for doc_id, queue in live.items():
                lane = self.docs[doc_id].lane
                for i, p in enumerate(queue):
                    kind[lane, i] = p.kind
                    client[lane, i] = p.ordinal
                    cseq[lane, i] = p.client_seq
                    ref[lane, i] = p.ref_seq
            raw = tk.RawOps(client=jnp.asarray(client),
                            client_seq=jnp.asarray(cseq),
                            ref_seq=jnp.asarray(ref),
                            kind=jnp.asarray(kind))
        with tracing.span("serving.dispatch", hist="serving.dispatch",
                          stage="ticket"):
            self.tstate, ticketed = tk.sequence_batched_strict(self.tstate,
                                                               raw)

        with tracing.span("serving.readback", hist="serving.readback",
                          stage="ticket"):
            seqs = np.asarray(ticketed.seq)
            msns = np.asarray(ticketed.min_seq)
            nacked = np.asarray(ticketed.nacked)
            not_joined = np.asarray(ticketed.not_joined)
            empty_after = np.asarray(ticketed.empty_after)
            next_seq = np.asarray(self.tstate.next_seq)
        _tkt1 = time.perf_counter()
        if bool(np.asarray(self.tstate.overflow).any()):
            raise RuntimeError("ticket client table overflow despite "
                               "pre-flush growth — invariant violation")

        merge_streams: Dict[tuple, List[HostOp]] = {}
        lww_streams: Dict[tuple, List[tuple]] = {}
        for doc_id, queue in live.items():
            lane = self.docs[doc_id].lane
            for i, p in enumerate(queue):
                seq = int(seqs[lane, i])
                if seq > 0:
                    sequenced = SequencedDocumentMessage.from_document_message(
                        p.msg, p.client_id, seq, int(msns[lane, i]))
                    sequenced.traces.append(ITrace.now("deli", "sequence"))
                    _tctx = tracing.message_context(p.msg)
                    if _tctx is not None:
                        # The op's ticket hop = this window's device
                        # ticketing (batched: one interval, one span per
                        # traced op riding it).
                        tracing.record_span("deli.ticket", _tctx,
                                            _tkt0, _tkt1, document=doc_id,
                                            seq=seq)
                    self.emit(doc_id, sequenced)
                    if p.kind == tk.MsgKind.OP and self.materialize:
                        self._collect_channel_op(
                            merge_streams, lww_streams, doc_id, p, seq,
                            int(msns[lane, i]))
                elif nacked[lane, i]:
                    reason = ("client not joined" if not_joined[lane, i]
                              else "refSeq below minimum sequence number")
                    self.nack(doc_id, p.client_id or "", Nack(
                        p.msg, int(next_seq[lane]) - 1,
                        NackContent(NACK_BAD_REF_SEQ, reason)))
                # NoClient with exact deli timing: windows cut right after
                # a LEAVE (_take_window), so a leave that empties the table
                # interposes NO_CLIENT before the doc's remaining backlog.
                if p.kind == tk.MsgKind.LEAVE and seq > 0 and \
                        empty_after[lane, i]:
                    self.pending.setdefault(doc_id, []).insert(0, _Pending(
                        tk.MsgKind.SYSTEM, -1, 0, 0, DocumentMessage(
                            client_sequence_number=0,
                            reference_sequence_number=int(
                                next_seq[lane]) - 1,
                            type=MessageType.NO_CLIENT), None))

        if self.materialize and merge_streams:
            self.merge.apply(merge_streams)
        if self.materialize and lww_streams:
            self.lww.apply(lww_streams)

    def _collect_channel_op(self, merge_streams: Dict[tuple, List[HostOp]],
                            lww_streams: Dict[tuple, List[tuple]],
                            doc_id: str, p: _Pending, seq: int,
                            msn: int,
                            seeded_before: Optional[Dict[tuple, int]] = None
                            ) -> None:
        """Route an admitted channel op to its device lane family:
        merge-tree ops to the segment kernel, map/cell/counter ops to the
        LWW kernel; anything else stays host-only."""
        if p.msg.type != MessageType.OPERATION:
            return
        contents = p.msg.contents
        if not isinstance(contents, dict):
            return
        envelope = contents.get("contents")
        if not isinstance(envelope, dict):
            return
        op = envelope.get("contents")
        key = (doc_id, contents.get("address"), envelope.get("address"))
        droute = directory_route(op)
        if droute is not None:
            self._route_directory(
                lww_streams,
                (doc_id, key[1], key[2] + DIR_SUFFIX),
                droute, op, seq, seeded_before)
            return
        route = matrix_route(op)
        if route is not None:
            # SharedMatrix: axis ops ride merge lanes under suffixed
            # channel keys, cell writes ride an LWW lane — the matrix
            # decomposes into the two families the device already serves.
            store, chan = key[1], key[2]
            if route == "cell":
                self._route_lww(
                    lww_streams, (doc_id, store, chan + MATRIX_CELLS_SUFFIX),
                    {"type": "set", "key": op["key"],
                     "value": op.get("value")},
                    seq, seeded_before)
            else:
                suffix = MATRIX_ROWS_SUFFIX if route == "rows" \
                    else MATRIX_COLS_SUFFIX
                self._route_merge(
                    merge_streams, (doc_id, store, chan + suffix),
                    op["op"], p, seq, msn, seeded_before)
        elif looks_like_merge_op(op):
            self._route_merge(merge_streams, key, op, p, seq, msn,
                              seeded_before)
        elif looks_like_lww_op(op):
            self._route_lww(lww_streams, key, op, seq, seeded_before)

    def _route_merge(self, merge_streams: Dict[tuple, List[HostOp]],
                     key: tuple, op: dict, p: _Pending, seq: int, msn: int,
                     seeded_before: Optional[Dict[tuple, int]]) -> None:
        if key in self.merge.opaque:
            return
        # Run payloads are modelable ONLY on matrix axis sub-lanes (their
        # extract path emits runs back); elsewhere they stay Unmodelable.
        # Items payloads ARE modelable on ordinary sequence lanes now
        # that extraction re-encodes them (round 5) — SharedNumber/
        # ObjectSequence channels materialize instead of dropping opaque.
        allow_runs = matrix_base_key(key) is not None
        if seeded_before is not None and seq <= seeded_before.get(key, 0):
            return  # already reflected in the seeded snapshot base
        if key not in self.merge.where:
            # First op for this channel: its base content may have
            # shipped in the attach/client summary — seed the lane
            # from storage before applying ops addressed against it.
            probe = self._probe_summary(key[0])
            if probe is not None:
                payload = probe.channels.get((key[1], key[2]))
                if payload is not None and seq > probe.sequence_number:
                    self.merge.seed(key, *payload)
        try:
            ops = wire_to_host_ops(self.merge.builder, op, seq,
                                   p.ref_seq, p.ordinal, msn,
                                   allow_runs=allow_runs,
                                   allow_items=not allow_runs)
        except Unmodelable:
            self.merge.drop(key)
            return
        merge_streams.setdefault(key, []).extend(ops)

    def _route_lww(self, lww_streams: Dict[tuple, List[tuple]], key: tuple,
                   op: dict, seq: int,
                   seeded_before: Optional[Dict[tuple, int]]) -> None:
        if key in self.lww.opaque:
            return
        if seeded_before is not None and seq <= seeded_before.get(key, 0):
            return  # already reflected in the seeded snapshot base
        if key not in self.lww.where:
            probe = self._probe_summary(key[0])
            if probe is not None:
                payload = probe.lww_channels.get((key[1], key[2]))
                if payload is not None and seq > probe.sequence_number:
                    self._seed_lww(key, payload, probe)
        try:
            lww_streams.setdefault(key, []).append(
                self.lww.wire_to_op(op, seq))
        except Unmodelable:
            pass

    def _route_directory(self, lww_streams: Dict[tuple, List[tuple]],
                         key: tuple, kind: str, op: dict, seq: int,
                         seeded_before: Optional[Dict[tuple, int]]
                         ) -> None:
        """SharedDirectory op -> the channel's LWW lane: (path, key)
        pairs intern as composite keys, a pathed clear expands to
        per-key deletes, and structural ops evolve the host path set
        that gates storage ops (object-path drop semantics for
        since-deleted subdirectories). Reference
        packages/dds/map/src/directory.ts:1624."""
        if key in self.lww.opaque:
            return
        if seeded_before is not None and seq <= seeded_before.get(key, 0):
            return  # already reflected in the seeded snapshot base
        if key not in self.lww.where:
            probe = self._probe_summary(key[0])
            if probe is not None:
                payload = probe.lww_channels.get((key[1], key[2]))
                if payload is not None and seq > probe.sequence_number:
                    self._seed_lww(key, payload, probe)
                    if key in self.lww.opaque:
                        return
        paths = self._dir_paths.setdefault(key, {"/"})

        def emit(wire_op):
            try:
                lww_streams.setdefault(key, []).append(
                    self.lww.wire_to_op(wire_op, seq))
            except Unmodelable:
                pass

        def lane_keys():
            """The channel's live composite keys: lane state + anything
            emitted earlier in THIS batch (not yet applied). Bounds
            clear/subtree-delete expansion to the channel, never the
            server-wide intern table."""
            names = set()
            snap = self.lww.snapshot(key)
            if snap is not None:
                names.update(snap["entries"])
            for (k_kind, kid, *_rest) in lww_streams.get(key, []):
                if kid >= 0:
                    name = self.lww.key_names[kid]
                    if k_kind == self.lww.lk.LwwKind.SET:
                        names.add(name)
                    else:
                        names.discard(name)
            return names

        if kind == "storage":
            path, kop = _norm_path(op["path"]), op["op"]
            if path not in paths:
                return  # object semantics: target subdir no longer exists
            t = kop.get("type")
            if t == "set" and isinstance(kop.get("key"), str):
                emit({"type": "set", "key": path + DIR_SEP + kop["key"],
                      "value": kop.get("value")})
            elif t == "delete" and isinstance(kop.get("key"), str):
                emit({"type": "delete",
                      "key": path + DIR_SEP + kop["key"]})
            elif t == "clear":
                # Path-scoped clear: expand to deletes over the
                # channel's keys under this exact path.
                prefix = path + DIR_SEP
                for name in sorted(lane_keys()):
                    if name.startswith(prefix):
                        emit({"type": "delete", "key": name})
            else:
                # Unknown storage-kernel shape: the lane can no longer
                # track the object path — degrade this one channel.
                self.lww.drop(key)
                self._dir_paths.pop(key, None)
        elif kind == "createSubDirectory":
            parent, name = _norm_path(op["path"]), op["name"]
            if DIR_SEP in name or "/" in name:
                # A separator-bearing name would make composite keys
                # ambiguous; a slash-bearing name is unresolvable by
                # get_working_directory on the clients themselves.
                # Degrade: the host object path remains authoritative.
                self.lww.drop(key)
                self._dir_paths.pop(key, None)
                return
            if parent in paths:
                paths.add(_child_path(parent, name))
            self.lww.lane_for(key)
            self.lww.mark_dirty(key)
        else:  # deleteSubDirectory
            child = _child_path(_norm_path(op["path"]), op["name"])
            gone = {p for p in paths
                    if p == child or p.startswith(child + "/")}
            if not gone:
                return
            paths -= gone
            for name in sorted(lane_keys()):
                p, sep, _ = name.partition(DIR_SEP)
                if sep and p in gone:
                    emit({"type": "delete", "key": name})
            self.lww.lane_for(key)
            self.lww.mark_dirty(key)

    # -- batched server-side summarization ---------------------------------
    def summarize_documents(self, chunk_chars: int = 10000,
                            only: Optional[set] = None
                            ) -> Dict[tuple, dict]:
        """Chunked snapshots of every materialized channel — merge-tree
        lanes (one batched device extraction per capacity bucket) AND LWW
        lanes (map/cell/counter entries + counter accumulator). `only`
        restricts to the given channel keys (incremental path)."""
        self.drain()  # settle any deferred window before reading lanes
        out = self.merge.extract_all(chunk_chars, only=only)
        for key in self.lww.where:
            if only is not None and key not in only:
                continue
            snap = self.lww.snapshot(key)
            if snap is not None:
                out[key] = {
                    "header": {
                        "kind": "lww",
                        "sequenceNumber": snap["sequenceNumber"],
                    },
                    "entries": snap["entries"],
                    "counter": snap["counter"],
                }
        _compose_matrix_channels(out)
        self._compose_directory_channels(out)
        return out

    def _compose_directory_channels(self, out: Dict[tuple, dict]) -> None:
        """Recombine directory lane snapshots (flattened composite-key
        entries + the host path set) into the nested root.to_dict() form
        under the real channel key."""
        for key in [k for k in out
                    if isinstance(k[2], str) and k[2].endswith(DIR_SUFFIX)]:
            part = out.pop(key)
            base = (key[0], key[1], key[2][:-len(DIR_SUFFIX)])
            out[base] = {
                "header": {
                    "kind": "directory",
                    "sequenceNumber": part["header"]["sequenceNumber"],
                },
                "directory": _nest_directory(
                    part.get("entries", {}),
                    self._dir_paths.get(key, {"/"})),
            }

    def summarize_documents_async(self, on_done,
                                  chunk_chars: int = 10000):
        """Pipeline-stage overlap (kafka-service/README.md:58-60): the
        device extraction is dispatched NOW (async on the accelerator
        queue); the D2H transfer + host snapshot assembly run on a worker
        thread while the caller keeps sequencing the next batch. The
        extracted device arrays are immutable, so subsequent flushes
        replacing the lane states cannot corrupt an in-flight summary."""
        import threading

        self.drain()  # settle any deferred window before reading lanes
        jobs, cached = self.merge.extract_dispatch(chunk_chars=chunk_chars)
        # LWW snapshots are host-cheap: capture them now so the composed
        # output matches the synchronous path (matrix cell stores).
        lww_part: Dict[tuple, dict] = {}
        for key in self.lww.where:
            snap = self.lww.snapshot(key)
            if snap is not None:
                lww_part[key] = {
                    "header": {"kind": "lww",
                               "sequenceNumber": snap["sequenceNumber"]},
                    "entries": snap["entries"],
                    "counter": snap["counter"],
                }
        # Directory composition reads the live path sets — do it now,
        # synchronously, so the worker thread never races a later flush.
        self._compose_directory_channels(lww_part)

        def work():
            try:
                out = self.merge.extract_assemble(jobs, chunk_chars, cached)
                out.update(lww_part)
                _compose_matrix_channels(out)
            finally:
                self.merge.extract_guard_release()
            on_done(out)

        # Hold fold/rescue payload frees while the worker resolves
        # through the shared table (a recycled id would materialize the
        # wrong text into this snapshot). Acquired last so a raise in
        # the synchronous staging above cannot leak the guard — and
        # released on a failed thread start (fd/thread exhaustion), or
        # every later free would defer forever.
        self.merge.extract_guard_acquire()
        try:
            th = threading.Thread(target=work, daemon=True)
            th.start()
        except BaseException:  # incl. KeyboardInterrupt: never leak the guard
            self.merge.extract_guard_release()
            raise
        return th

    # -- introspection (tests / summarization) -----------------------------
    def channel_text(self, doc_id: str, store: str,
                     channel: str) -> Optional[str]:
        """Server-materialized text for a channel (device state + host
        payload table) — the batched-summarization read path."""
        self.drain()
        return self.merge.text((doc_id, store, channel))

    def channel_snapshot(self, doc_id: str, store: str,
                         channel: str) -> Optional[dict]:
        """Server-materialized LWW channel state (map entries / cell value
        under the reserved key / counter accumulator)."""
        self.drain()
        return self.lww.snapshot((doc_id, store, channel))

    def channel_matrix(self, doc_id: str, store: str,
                       channel: str) -> Optional[list]:
        """Server-materialized matrix grid (rows-in-order × cols-in-order
        of cell values) from the two axis merge lanes + the cell-store
        LWW lane — comparable 1:1 with SharedMatrix.extract() on a caught-
        up client. None if no matrix sub-lane exists for the channel."""
        from ..mergetree.runs import Run, id_key

        self.drain()

        def axis_ids(suffix: str) -> list:
            entries = self.merge.entries((doc_id, store, channel + suffix))
            ids: list = []
            for e in entries or []:
                if e.get("removedSeq") is not None or \
                        e.get("removedLocalSeq") is not None:
                    continue
                text = e.get("text")
                if isinstance(text, Run):
                    ids.extend(text.ids())
            return ids

        rows_known = (doc_id, store,
                      channel + MATRIX_ROWS_SUFFIX) in self.merge.where
        cols_known = (doc_id, store,
                      channel + MATRIX_COLS_SUFFIX) in self.merge.where
        cells_snap = self.lww.snapshot(
            (doc_id, store, channel + MATRIX_CELLS_SUFFIX))
        if not rows_known and not cols_known and cells_snap is None:
            return None
        cells = cells_snap["entries"] if cells_snap else {}
        row_ids = axis_ids(MATRIX_ROWS_SUFFIX)
        col_ids = axis_ids(MATRIX_COLS_SUFFIX)
        return [[cells.get(id_key(r) + "|" + id_key(c))
                 for c in col_ids] for r in row_ids]

    def channel_items(self, doc_id: str, store: str,
                      channel: str) -> Optional[list]:
        """Server-materialized item-sequence values (visible Items
        payloads in order) — comparable 1:1 with get_items() on a
        caught-up client, including its behavior on non-items lanes
        (Items segments only; a pure text lane reads as []). None when
        no lane exists."""
        from ..mergetree.oracle import Items

        self.drain()
        entries = self.merge.entries((doc_id, store, channel))
        if entries is None:
            return None
        out: list = []
        for e in entries:
            if e.get("removedSeq") is not None or \
                    e.get("removedLocalSeq") is not None:
                continue
            text = e.get("text")
            if isinstance(text, Items):
                out.extend(text.values)
        return out

    def channel_directory(self, doc_id: str, store: str,
                          channel: str) -> Optional[dict]:
        """Server-materialized directory tree in root.to_dict() form
        (nested storage + subdirectories) from the channel's LWW lane +
        host path set — comparable 1:1 with SharedDirectory.root.to_dict()
        on a caught-up client. None when no directory lane exists."""
        self.drain()
        key = (doc_id, store, channel + DIR_SUFFIX)
        snap = self.lww.snapshot(key)
        if snap is None and key not in self._dir_paths:
            return None
        return _nest_directory(snap["entries"] if snap else {},
                               self._dir_paths.get(key, {"/"}))

    # -- read-path catch-up artifacts (server/readpath.py) -----------------
    def catchup_docs_supported(self) -> Tuple[Dict[str, List[tuple]], set]:
        """Partition the resident documents for the delta publisher:
        (doc -> its merge lane keys, unsupported doc ids). A document
        rides the delta path only when EVERY channel of it is a plain
        merge-tree sequence lane the publisher can translate — any LWW/
        matrix/directory lane, any opaque (unmodelable-op) channel, or
        any catchup_unsafe seed excludes the whole document: a partial
        artifact would desync the client's per-doc seq bookkeeping, so
        those documents keep the tail-replay fallback."""
        by_doc: Dict[str, List[tuple]] = {}
        unsupported: set = set()
        for key in list(self.merge.where):
            by_doc.setdefault(key[0], []).append(key)
            chan = key[2]
            if (isinstance(chan, str) and "\x00" in chan) \
                    or key in self.merge.catchup_unsafe:
                unsupported.add(key[0])
        for key in list(self.lww.where):
            unsupported.add(key[0])
        for key in list(self.merge.opaque) + list(self.lww.opaque):
            unsupported.add(key[0])
        return by_doc, unsupported

    def catchup_snapshot(self, only_docs: Optional[set] = None,
                         chunk_chars: int = 10000) -> Dict[str, dict]:
        """One read-tier refresh epoch: extract every supported document
        whose change generations advanced past its published artifact —
        ONE batched device dispatch per capacity bucket / page group for
        ALL of them together (extract_dispatch; clean lanes ride the
        summarize blob cache) — and return the per-doc artifact bodies
        {doc_id: {"seq", "gen", "clients", "channels"}}. Channel entries
        are narrow-wire packed (mergetree.catchup.pack_entries_narrow)
        with client fields translated from this lambda's interned
        ordinals to indices into the per-doc wire-client table. Server
        cost is proportional to DIRTY documents, never to connecting
        clients; the caller (TpuLocalServer.refresh_catchup / an
        external publisher) joins in the protocol half and publishes."""
        with tracing.span("catchup.refresh", root=True,
                          hist="catchup.refresh"):
            return self._catchup_snapshot_traced(only_docs, chunk_chars)

    def _catchup_snapshot_traced(self, only_docs: Optional[set],
                                 chunk_chars: int) -> Dict[str, dict]:
        from ..mergetree.catchup import (pack_entries_narrow,
                                         translate_entry_clients)

        self.drain()
        by_doc, unsupported = self.catchup_docs_supported()
        refresh: Dict[str, int] = {}  # doc -> gen this epoch covers
        for doc_id, keys in by_doc.items():
            if only_docs is not None and doc_id not in only_docs:
                continue
            if doc_id in unsupported or doc_id not in self.docs:
                continue
            doc_gen = max((self.merge.change_gen.get(k, 0) for k in keys),
                          default=0)
            if doc_gen <= self._catchup_gen.get(doc_id, -1):
                continue  # published artifact already covers this state
            refresh[doc_id] = doc_gen
        if not refresh:
            return {}
        want = {k for d in refresh for k in by_doc[d]}
        jobs, cached = self.merge.extract_dispatch(only=want,
                                                   chunk_chars=chunk_chars)
        increment("catchup.refresh_dispatches", len(jobs))
        snaps = self.merge.extract_assemble(jobs, chunk_chars, cached)
        out: Dict[str, dict] = {}
        for doc_id, doc_gen in refresh.items():
            dl = self.docs[doc_id]
            # Ordinal -> client-table index, via the wire ids this lane
            # interned; entries fail the translation (KeyError) only for
            # ordinal spaces the publisher cannot disambiguate, which
            # excludes the doc this epoch (fallback stays correct).
            clients = [dl.ordinals[o] for o in sorted(dl.ordinals)]
            mapping = {o: i for i, o in enumerate(sorted(dl.ordinals))}
            doc_seq = self.document_seq(doc_id)
            channels: List[list] = []
            ok = True
            for key in by_doc[doc_id]:
                snap = snaps.get(key)
                if snap is None:
                    ok = False
                    break
                entries = [e for chunk in snap["chunks"] for e in chunk]
                try:
                    entries = translate_entry_clients(entries, mapping)
                    blob = pack_entries_narrow(entries, base_seq=doc_seq)
                except (KeyError, ValueError):
                    ok = False
                    break
                channels.append([key[1], key[2], dict(snap["header"]),
                                 blob])
            if not ok:
                increment("catchup.refresh_unsupported")
                continue
            out[doc_id] = {"seq": doc_seq, "gen": doc_gen,
                           "clients": clients, "channels": channels}
        increment("catchup.refresh_docs", len(out))
        return out

    def catchup_mark_published(self, doc_id: str, gen: int) -> None:
        """Advance the publish watermark — called only after the joined
        artifact actually landed in a CatchupCache."""
        if gen > self._catchup_gen.get(doc_id, -1):
            self._catchup_gen[doc_id] = gen

    def document_seq(self, doc_id: str) -> int:
        dl = self.docs.get(doc_id)
        if dl is None:
            return 0
        return int(np.asarray(self.tstate.next_seq)[dl.lane]) - 1

    def doc_sequence_numbers(self) -> Dict[str, int]:
        """Per-document head sequence number: the `ticketed` watermark
        feed (telemetry/watermarks.py), pulled at scrape time — one
        next_seq read for the whole fleet, zero per-op cost."""
        if not self.docs:
            return {}
        next_seq = np.asarray(self.tstate.next_seq)
        return {doc: int(next_seq[dl.lane]) - 1
                for doc, dl in self.docs.items()}

    def close(self) -> None:
        # Graceful close persists progress; pending (unflushed) messages are
        # NOT emitted here — a crash-restart replays them from the last
        # committed offset, the same at-least-once window as the scalar deli.
        self.drain()
        self._checkpoint()


def _detail(msg: DocumentMessage):
    if msg.data is not None:
        return json.loads(msg.data)
    return msg.contents or {}
