"""Minimal RFC 6455 WebSocket codec: server-side upgrade + both-side frame
I/O + a blocking client.

Role parity with the reference's socket.io transport
(`drivers/driver-base/src/documentDeltaConnection.ts`, alfred `io.ts`):
the live delta stream between clients and the front door rides websockets.
The reference pulls in socket.io/engine.io; here the framing layer is
~200 lines of stdlib because the delta protocol (JSON text frames, see
`server/alfred.py`) needs nothing beyond text messages + clean close.

Not implemented (not needed for the delta protocol): extensions
(permessage-deflate), subprotocol negotiation, fragmented continuation
frames spanning >2**63 bytes.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading
from typing import Optional, Tuple

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


class WebSocketClosed(Exception):
    pass


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WebSocketClosed("socket closed mid-frame")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> Tuple[int, bytes]:
    """Read one frame; returns (opcode, payload). Handles masked payloads
    and 16/64-bit extended lengths. Fragmented messages are reassembled by
    WebSocketConnection.recv()."""
    header = _recv_exact(sock, 2)
    fin_op, mask_len = header[0], header[1]
    opcode = fin_op & 0x0F
    fin = bool(fin_op & 0x80)
    masked = bool(mask_len & 0x80)
    length = mask_len & 0x7F
    if length == 126:
        length = struct.unpack(">H", _recv_exact(sock, 2))[0]
    elif length == 127:
        length = struct.unpack(">Q", _recv_exact(sock, 8))[0]
    mask = _recv_exact(sock, 4) if masked else None
    payload = _recv_exact(sock, length) if length else b""
    if mask:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    # Encode fin in bit 4 of the returned opcode for the reassembly loop.
    return (opcode | (0x10 if fin else 0)), payload


def write_frame(sock: socket.socket, opcode: int, payload: bytes,
                mask: bool) -> None:
    header = bytearray([0x80 | opcode])
    length = len(payload)
    mask_bit = 0x80 if mask else 0
    if length < 126:
        header.append(mask_bit | length)
    elif length < (1 << 16):
        header.append(mask_bit | 126)
        header += struct.pack(">H", length)
    else:
        header.append(mask_bit | 127)
        header += struct.pack(">Q", length)
    if mask:
        key = os.urandom(4)
        header += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    sock.sendall(bytes(header) + payload)


class WebSocketConnection:
    """Framed text-message channel over an already-upgraded socket.
    Thread-safe sends (one writer lock); single reader expected."""

    def __init__(self, sock: socket.socket, is_client: bool):
        self.sock = sock
        self.is_client = is_client  # clients mask outgoing frames
        self._send_lock = threading.Lock()
        self._closed = False

    def send_text(self, text: str) -> None:
        if self._closed:
            raise WebSocketClosed("connection closed")
        with self._send_lock:
            write_frame(self.sock, OP_TEXT, text.encode(), self.is_client)

    def recv(self) -> str:
        """Block until a full text message arrives. Transparently answers
        pings; raises WebSocketClosed on close frame or dead socket."""
        fragments = []
        while True:
            try:
                op_fin, payload = read_frame(self.sock)
            except (OSError, WebSocketClosed):
                self._closed = True
                raise WebSocketClosed("connection closed")
            opcode, fin = op_fin & 0x0F, bool(op_fin & 0x10)
            if opcode == OP_CLOSE:
                self.close(reply=True)
                raise WebSocketClosed("close frame received")
            if opcode == OP_PING:
                with self._send_lock:
                    write_frame(self.sock, OP_PONG, payload, self.is_client)
                continue
            if opcode == OP_PONG:
                continue
            if opcode in (OP_TEXT, OP_BINARY, OP_CONT):
                fragments.append(payload)
                if fin:
                    return b"".join(fragments).decode()

    def close(self, reply: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            with self._send_lock:
                write_frame(self.sock, OP_CLOSE, b"", self.is_client)
        except OSError:
            pass
        if not reply:
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self.sock.close()

    @property
    def closed(self) -> bool:
        return self._closed


def upgrade_server_socket(sock: socket.socket,
                          client_key: str) -> WebSocketConnection:
    """Complete the server side of the upgrade handshake. The HTTP request
    line/headers were already consumed by the HTTP server; this writes the
    101 response and hands back a framed connection."""
    response = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(client_key)}\r\n"
        "\r\n"
    )
    sock.sendall(response.encode())
    return WebSocketConnection(sock, is_client=False)


def connect(host: str, port: int, path: str = "/",
            timeout: Optional[float] = None) -> WebSocketConnection:
    """Blocking client: TCP connect + upgrade handshake."""
    sock = socket.create_connection((host, port), timeout=timeout)
    key = base64.b64encode(os.urandom(16)).decode()
    request = (
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}:{port}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n"
        "\r\n"
    )
    sock.sendall(request.encode())
    # Read the 101 response headers.
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise WebSocketClosed("handshake failed: socket closed")
        buf += chunk
    status_line = buf.split(b"\r\n", 1)[0].decode()
    if " 101 " not in status_line + " ":
        raise WebSocketClosed(f"handshake rejected: {status_line}")
    headers = {}
    for line in buf.split(b"\r\n\r\n", 1)[0].split(b"\r\n")[1:]:
        name, _, value = line.decode().partition(":")
        headers[name.strip().lower()] = value.strip()
    if headers.get("sec-websocket-accept") != accept_key(key):
        raise WebSocketClosed("handshake failed: bad accept key")
    return WebSocketConnection(sock, is_client=True)
