"""Batched LWW kernel: map/cell/counter ops across thousands of channels.

The merge-tree kernel covers the sequence family; this covers the
last-write-wins family (SharedMap set/delete/clear — mapKernel.ts:490
remote-apply semantics, SharedCell setCell/deleteCell, SharedCounter
increment), so the TPU sequencer materializes EVERY common channel type
on device (server/tpu_sequencer.py routes ops here).

State per channel lane: a fixed-capacity key-slot table (interned key id,
payload ref, writer seq) + an additive counter accumulator. One op per
channel per scan step, `scan(T) x vmap(B)` like the other kernels; values
stay host-side behind integer payload refs (SURVEY.md §7: JSON stays on
the host)."""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LwwKind:
    NOOP = 0
    SET = 1     # key slot <- val ref (insert or overwrite)
    DELETE = 2  # free the key's slot
    CLEAR = 3   # free every slot
    ADD = 4     # counter += delta


class LwwState(NamedTuple):
    """[B, C] slot tables + per-lane scalars (leading batch axis)."""

    key: jnp.ndarray      # interned key id; -1 = free slot
    val: jnp.ndarray      # payload ref of the latest write
    seq: jnp.ndarray      # sequence number of the latest write
    counter: jnp.ndarray  # [B] additive accumulator
    last_seq: jnp.ndarray  # [B] high-water mark of applied ops
    overflow: jnp.ndarray  # [B] bool: a SET found no free slot


class LwwOps(NamedTuple):
    """[B, T] op columns (NOOP-padded)."""

    kind: jnp.ndarray
    key: jnp.ndarray
    val: jnp.ndarray
    delta: jnp.ndarray
    seq: jnp.ndarray


def make_lww_state(capacity: int, batch: int | None = None) -> LwwState:
    def shape(*dims):
        return dims if batch is None else (batch, *dims)
    return LwwState(
        key=jnp.full(shape(capacity), -1, jnp.int32),
        val=jnp.full(shape(capacity), -1, jnp.int32),
        seq=jnp.zeros(shape(capacity), jnp.int32),
        counter=jnp.zeros(shape(), jnp.int32),
        last_seq=jnp.zeros(shape(), jnp.int32),
        overflow=jnp.zeros(shape(), jnp.bool_),
    )


def _apply_one(s: LwwState, kind, key, val, delta, seq) -> LwwState:
    c = s.key.shape[-1]
    idx = jnp.arange(c, dtype=jnp.int32)
    is_set = kind == LwwKind.SET
    is_del = kind == LwwKind.DELETE
    is_clear = kind == LwwKind.CLEAR
    is_add = kind == LwwKind.ADD
    is_op = is_set | is_del | is_clear | is_add

    match = s.key == key
    have = jnp.any(match)
    free = s.key == -1
    # SET: existing slot wins; else first free slot.
    target = jnp.where(have, jnp.argmax(match),
                       jnp.argmax(free)).astype(jnp.int32)
    can_set = is_set & (have | jnp.any(free))
    at = idx == target
    new_key = jnp.where(can_set & at, key, s.key)
    new_val = jnp.where(can_set & at, val, s.val)
    new_seq = jnp.where(can_set & at, seq, s.seq)
    # DELETE: free the matching slot (LWW remote semantics — the server has
    # no pending-local shadowing, mapKernel.ts:619 reduces to this).
    gone = is_del & match
    new_key = jnp.where(gone, -1, new_key)
    new_val = jnp.where(gone, -1, new_val)
    # CLEAR: free everything.
    new_key = jnp.where(is_clear, -1, new_key)
    new_val = jnp.where(is_clear, -1, new_val)
    return LwwState(
        key=new_key, val=new_val, seq=new_seq,
        counter=s.counter + jnp.where(is_add, delta, 0),
        last_seq=jnp.where(is_op, jnp.maximum(s.last_seq, seq), s.last_seq),
        overflow=s.overflow | (is_set & ~have & ~jnp.any(free)),
    )


def _scan(state: LwwState, ops: LwwOps, batched: bool) -> LwwState:
    steps = ops.kind.shape[-1]

    def body(s, t):
        if batched:
            s2 = jax.vmap(lambda sd, k, ky, v, d, q: _apply_one(
                sd, k[t], ky[t], v[t], d[t], q[t]))(
                s, ops.kind, ops.key, ops.val, ops.delta, ops.seq)
        else:
            s2 = _apply_one(s, ops.kind[t], ops.key[t], ops.val[t],
                            ops.delta[t], ops.seq[t])
        return s2, None

    out, _ = jax.lax.scan(body, state, jnp.arange(steps, dtype=jnp.int32))
    return out


@jax.jit
# fluidlint: disable=MISSING_DONATE — non-donating by design (docstring):
# overflow lanes restore and re-apply from the retained pre-state.
def apply_lww_batched(state: LwwState, ops: LwwOps) -> LwwState:
    """Apply [B, T] LWW op streams to B channels (non-donating: callers
    retry overflowing lanes at a larger capacity from the retained input)."""
    return _scan(state, ops, batched=True)


def grow_lane_capacity(state: LwwState, capacity: int) -> LwwState:
    """Re-pad every lane's slot table (overflow recovery)."""
    b, c = state.key.shape
    if capacity <= c:
        return state

    def widen(col, fill):
        out = jnp.full((b, capacity), fill, col.dtype)
        return out.at[:, :c].set(col)

    return state._replace(key=widen(state.key, -1),
                          val=widen(state.val, -1),
                          seq=widen(state.seq, 0),
                          overflow=jnp.zeros((b,), jnp.bool_))
